"""``fixpoint`` — the dataflow core's iterate-to-convergence primitive.

Two halves, mirroring Spark's split between an RDD program and the driver
that schedules it:

- :func:`iterate` is the **in-jit combinator**: one ``lax.scan`` /
  ``lax.while_loop`` skeleton carrying ``(state, delta, iters)``, shared
  by every fixpoint workload (single-chip and sharded PageRank, batched
  personalized PageRank, HITS, connected components).  Before the
  dataflow port each runner re-implemented this loop privately; a
  convergence fix now lands once.
- :func:`run_segments` is the **host driver**: run the compiled loop in
  checkpoint-sized segments with the resilience ladder (retry → elastic
  mesh shrink / CPU re-lowering → ``ResilienceExhausted`` + checkpoint)
  and the obs spans attached ONCE, underneath every workload.  This is
  the code that moved here from ``models/driver.py`` (which still
  re-exports it): the Spark counterpart is the DAGScheduler driving an
  iterative job, and the reason it lives in ``dataflow/`` is the ISSUE 9
  marginal-cost claim — a new fixpoint workload gets checkpointing,
  elastic degradation and tracing by *calling* this, not by copying it.

``run_segments`` is workload-agnostic: ``cfg`` is duck-typed (any frozen
config with ``iterations`` / ``tol`` / ``checkpoint_every`` /
``checkpoint_dir`` / ``config_hash()``), and ``site_prefix`` names the
guarded sites and spans (``pagerank`` for the ported runners, ``hits`` /
``cc`` / ``ppr`` for the new workloads) so traces and chaos plans stay
per-workload addressable.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, NamedTuple

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


def commit_barrier(
    drain_all: Callable[[], None],
    commit: Callable[[], None],
    save_checkpoint: Callable[[], None] | None = None,
) -> None:
    """The drain-before-commit barrier of the staged ingest pipeline
    (ISSUE 10): every in-flight launch drains, THEN device carry state is
    pulled, THEN (optionally) the snapshot is written — so a checkpoint
    can never hold carry contributions from chunks it does not record as
    ingested, no matter how deep the H2D staging / in-flight windows run.

    Lives here rather than in ``dataflow/ingest.py`` because it is the
    ingest counterpart of the fixpoint checkpoint discipline above (a
    segment must complete before its snapshot): one module owns "what a
    commit point means" for both dataflow driver shapes.  The span makes
    barrier stalls attributable in traces — time spent here is pipeline
    drain, not compute."""
    with obs.span("ingest.commit_barrier"):
        drain_all()
        commit()
        if save_checkpoint is not None:
            save_checkpoint()


def default_delta(new, old):
    """L1 distance between successive carries — PageRank's convergence
    gauge, and a sane default for any single-array fixpoint."""
    import jax.numpy as jnp

    return jnp.sum(jnp.abs(new - old))


def iterate(
    step: Callable,
    carry0,
    *,
    iterations: int,
    tol: float = 0.0,
    delta_fn: Callable = default_delta,
):
    """The dataflow ``iterate`` primitive (Spark's driver ``for`` loop over
    a cached RDD, fused into ONE XLA program — zero host round-trips
    between iterations).

    Runs ``step(carry) -> carry`` to a fixpoint inside the enclosing jit:
    ``lax.scan`` for fixed ``iterations`` (tol == 0), ``lax.while_loop``
    carrying the delta for tolerance runs.  ``delta_fn(new, old)`` is the
    convergence gauge (scalar; compared against ``tol``).  Returns
    ``(carry, iters_done, last_delta)``; with ``iterations == 0`` the
    delta is ``inf`` (nothing measured).

    Must be called under ``jax.jit`` (the runner owns donation of the
    carry buffer — see ``ops.pagerank.make_pagerank_runner``).
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(carry0)
    delta_dtype = leaves[0].dtype if leaves else jnp.float32
    if not jnp.issubdtype(delta_dtype, jnp.floating):
        # integer carries (label propagation) still need a float delta
        # slot: the while_loop init is inf, and delta_fn must return this
        # dtype (components uses a changed-label count cast to f32)
        delta_dtype = jnp.float32

    if tol > 0.0:
        def cond(state):
            _, delta, it = state
            return jnp.logical_and(delta > tol, it < iterations)

        def body(state):
            carry, _, it = state
            new = step(carry)
            return new, delta_fn(new, carry), it + 1

        init = (carry0, jnp.array(jnp.inf, delta_dtype),
                jnp.array(0, jnp.int32))
        carry, delta, it = jax.lax.while_loop(cond, body, init)
        return carry, it, delta

    def body(carry, _):
        new = step(carry)
        return new, delta_fn(new, carry)

    carry, deltas = jax.lax.scan(body, carry0, None, length=iterations)
    last = deltas[-1] if iterations > 0 else jnp.array(jnp.inf, delta_dtype)
    return carry, jnp.array(iterations, jnp.int32), last


def checkpoint_salvage(cfg, init_state: Callable[[], np.ndarray]):
    """``(at_iter, state_np)`` from the newest checkpoint, else
    ``(0, init_state())`` — what a device-loss rung restarts the
    uncommitted span from (the live carry died with the device)."""
    if cfg.checkpoint_dir:
        latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
        if latest is not None:
            step, arrays, _ = ckpt.load_checkpoint(latest, cfg.config_hash())
            return int(step), arrays["ranks"]
    return 0, init_state()


def make_cpu_salvage(
    cfg,
    metrics: MetricsRecorder,
    *,
    site_prefix: str,
    init_state: Callable[[], np.ndarray],
    cpu_exec: Callable,
    make_runner: Callable,
    extract_np: Callable,
):
    """The single-chip elastic salvage rung, built ONCE here for every
    fixpoint workload (the sharded counterpart lives in
    parallel/pagerank_sharded.py): a *device-attributed* loss — including
    one first surfacing at a delta-sync or checkpoint-pull site, where
    the donated carry is already dead — is acknowledged in the health
    registry, the newest snapshot (else the init vector) is salvaged, and
    the uncommitted span re-runs on the CPU backend from HOST state.
    Whole-backend faults (no device index) raise through to the legacy
    cpu rung / exhausted path, preserving the pre-existing ladder.

    ``cpu_exec(rerun_cfg, state_np) -> (state_dev, iters, delta,
    invoke)``: re-lower and run on CPU, returning the replacement
    ``invoke`` every subsequent segment uses.  Plug the result into
    :func:`run_segments`'s ``elastic_rebuild`` parameter.
    """

    def rebuild(exc, rd, done, seg_cfg):
        lost = elastic.unwrap_device_loss(exc)
        idx = elastic.device_index(lost) if lost is not None else None
        if not elastic.enabled() or idx is None:
            raise exc
        elastic.health().mark_lost(idx)
        at_iter, state = checkpoint_salvage(cfg, init_state)
        todo = done - at_iter + seg_cfg.iterations
        obs.emit("degraded", site=f"{site_prefix}_step", ladder="cpu",
                 salvage_iter=at_iter, rerun_iters=todo,
                 error=f"{type(exc).__name__}: {exc}"[:200])
        obs.counter("degraded")
        metrics.record(event="degraded", site=f"{site_prefix}_step",
                       ladder="cpu", salvage_iter=at_iter, rerun_iters=todo)
        with obs.span(f"{site_prefix}.cpu_salvage", at_iter=at_iter,
                      todo=todo):
            rerun_cfg = dataclasses.replace(
                seg_cfg, iterations=todo, checkpoint_every=0,
                checkpoint_dir=None,
            )
            rd2, iters, delta, invoke2 = cpu_exec(rerun_cfg, state)
        return ElasticResult(
            rd2, at_iter + int(iters) - done, float(delta),
            make_runner, invoke2, extract_np, {"backend": "cpu"},
        )

    return rebuild


def make_pull_salvage(
    cfg,
    metrics: MetricsRecorder,
    *,
    site_prefix: str,
    init_state: Callable[[], np.ndarray],
    cpu_exec: Callable,
    get_done: Callable[[], int],
):
    """The RESULT-pull counterpart of :func:`make_cpu_salvage`, shared by
    every single-chip fixpoint (and models/pagerank.py): a
    device-attributed loss first surfacing at ``{site_prefix}_result_pull``
    — no segment dispatch left to catch it — acknowledges the loss,
    salvages the newest snapshot, re-runs the uncommitted span on the CPU
    backend and pulls from the CPU buffers (the loss is acknowledged, so
    chaos cannot re-fire at the same site).  Returns a ``fallbacks`` rung
    for the final ``rx.device_get``."""

    def pull_salvage(exc):
        lost = elastic.unwrap_device_loss(exc)
        idx = elastic.device_index(lost) if lost is not None else None
        if not elastic.enabled() or idx is None:
            raise exc
        elastic.health().mark_lost(idx)
        at_iter, state = checkpoint_salvage(cfg, init_state)
        done = int(get_done())
        todo = done - at_iter
        site = f"{site_prefix}_result_pull"
        obs.emit("degraded", site=site, ladder="cpu",
                 salvage_iter=at_iter, rerun_iters=todo,
                 error=f"{type(exc).__name__}: {exc}"[:200])
        obs.counter("degraded")
        metrics.record(event="degraded", site=site, ladder="cpu",
                       salvage_iter=at_iter, rerun_iters=todo)
        with obs.span(f"{site_prefix}.cpu_salvage", at_iter=at_iter,
                      todo=todo):
            dtype = init_state().dtype
            if todo <= 0:
                return np.asarray(state).astype(dtype)
            rerun_cfg = dataclasses.replace(
                cfg, iterations=todo, checkpoint_every=0, checkpoint_dir=None
            )
            rd2, _iters, _delta, _invoke = cpu_exec(rerun_cfg, state)
            return rx.device_get(
                rd2, site=site, metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )

    return pull_salvage


def run_single_chip_fixpoint(
    cfg,
    metrics: MetricsRecorder,
    *,
    site_prefix: str,
    init_state: Callable[[], np.ndarray],
    make_runner: Callable,
    build_operands: Callable[[], tuple],
    call: Callable,
):
    """The whole single-chip host driver for a fixpoint workload, shared
    wiring in one place (PPR / HITS / connected components run through
    this; models/pagerank.py keeps its own driver for resume +
    spark_exact): guarded delta-sync fetch (own site, so a transient
    failure never re-dispatches into the donated carry), checkpoint-pull
    and result-pull sites, the CPU re-lowering rung, the elastic salvage
    rung (:func:`make_cpu_salvage`), and the segment loop.

    - ``build_operands()`` builds the non-carry device operands (graph
      layout, teleport matrix, ...) from HOST state for the *current*
      default device — called once up front and again inside the CPU
      rungs, so recovery never reads a dead device buffer;
    - ``call(runner, operands, carry)`` invokes the compiled runner with
      the workload's argument order, returning ``(carry, iters, delta)``
      un-synced.

    Returns ``(state_np, iterations, last_delta)``.
    """
    import jax

    state0 = init_state()
    state_dtype = state0.dtype
    with Timer() as t_put:
        operands = build_operands()
    metrics.record(event="put_graph", preprocess_secs=t_put.elapsed)
    state_dev = jax.device_put(state0)

    def make_invoke(ops_tuple):
        def invoke(runner, rd):
            rd, iters, delta = call(runner, ops_tuple, rd)
            with obs.span(f"{site_prefix}.delta_sync"):
                delta = float(rx.device_get(
                    delta, site=f"{site_prefix}_delta_sync", metrics=metrics,
                    checkpoint_dir=cfg.checkpoint_dir,
                ))
            return rd, iters, delta

        return invoke

    def extract_np(rd):
        with obs.span(f"{site_prefix}.ckpt_pull"):
            return rx.device_get(
                rd, site=f"{site_prefix}_ckpt_pull", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )

    def make_cpu_invoke(seg_cfg):
        runner = make_runner(seg_cfg)

        def cpu_invoke(rd):
            with obs.span(f"{site_prefix}.cpu_degrade"):
                cpu = jax.devices("cpu")[0]
                with jax.default_device(cpu):
                    cpu_ops = build_operands()
                    rd_cpu = jax.device_put(rx.device_get(
                        rd, site=f"{site_prefix}_cpu_pull"
                    ), cpu)
                    out, iters, delta = call(runner, cpu_ops, rd_cpu)
                    delta = float(delta)
            return out, iters, delta

        return cpu_invoke

    def cpu_salvage_exec(rerun_cfg, state_np):
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            cpu_ops = build_operands()
            rd_cpu = jax.device_put(
                np.asarray(state_np).astype(state_dtype), cpu
            )
            runner = make_runner(rerun_cfg)
            rd2, iters, delta = call(runner, cpu_ops, rd_cpu)
            delta = float(delta)
        return rd2, int(iters), delta, make_invoke(cpu_ops)

    state_dev, done, last_delta = run_segments(
        cfg, metrics, state_dev, 0,
        make_runner=make_runner,
        invoke=make_invoke(operands),
        extract_np=extract_np,
        make_cpu_invoke=make_cpu_invoke,
        elastic_rebuild=make_cpu_salvage(
            cfg, metrics, site_prefix=site_prefix, init_state=init_state,
            cpu_exec=cpu_salvage_exec, make_runner=make_runner,
            extract_np=extract_np,
        ),
        site_prefix=site_prefix,
    )
    with obs.span(f"{site_prefix}.result_pull"):
        state_np = rx.device_get(
            state_dev, site=f"{site_prefix}_result_pull", metrics=metrics,
            checkpoint_dir=cfg.checkpoint_dir,
            fallbacks=[(None, make_pull_salvage(
                cfg, metrics, site_prefix=site_prefix,
                init_state=init_state, cpu_exec=cpu_salvage_exec,
                get_done=lambda: done,
            ))],
        )
    return state_np, done, last_delta


class ElasticResult(NamedTuple):
    """What an elastic shrink handler returns after it rebuilt the mesh
    and ran the failed segment on the survivors: the segment outputs plus
    the replacement callables every *subsequent* segment must use."""

    ranks_dev: object
    iters: int  # effective NEW iterations relative to the pre-failure count
    delta: float
    make_runner: Callable
    invoke: Callable
    extract_np: Callable
    metrics_extra: dict  # merged into per-segment metrics (e.g. devices=N)


def run_segments(
    cfg,
    metrics: MetricsRecorder,
    ranks_dev,
    start_iter: int,
    *,
    make_runner: Callable,
    invoke: Callable,
    extract_np: Callable[[object], np.ndarray],
    segments_allowed: bool = True,
    extra_metrics: dict | None = None,
    make_cpu_invoke: Callable | None = None,
    elastic_rebuild: Callable | None = None,
    site_prefix: str = "pagerank",
):
    """Run ``cfg.iterations`` in checkpoint-sized compiled segments.

    - ``make_runner(seg_cfg)`` compiles the loop for one segment length;
      called at most twice (body segments + tail) thanks to caching here.
    - ``invoke(runner, ranks_dev)`` executes and returns
      ``(ranks_dev, iters_done, delta)`` with a completed host sync.
    - ``extract_np(ranks_dev)`` yields the checkpointable state array.
    - ``make_cpu_invoke(seg_cfg)``, when given, builds the degradation-
      ladder rung: a ``ranks_dev -> (ranks_dev, iters, delta)`` callable
      re-lowered for the CPU backend, run when on-device retries are
      exhausted or the device is lost.
    - ``elastic_rebuild(exc, ranks_dev, done, seg_cfg)``, when given, is
      the mesh-shrink rung for sharded runners (and the single-chip
      checkpoint-salvage rung — models/pagerank.py): on device loss it
      salvages the current state, rebuilds over the survivors,
      repartitions, runs the failed segment there, and returns an
      :class:`ElasticResult` whose callables replace this loop's (the
      runner cache is dropped — every compiled program was welded to the
      dead mesh).  It raises when it does not apply (not a device loss,
      elastic disabled, nothing survives), passing the ladder on.

    Each segment dispatch runs under the resilience executor: transient
    failures retry with backoff (the runner is functional, so re-invoking
    with the same ranks cannot double-apply iterations), persistent ones
    walk the rungs above, and exhaustion raises ``ResilienceExhausted``
    carrying the latest checkpoint under ``cfg.checkpoint_dir``.  The
    single-chip runners *donate* their rank carry (ops/pagerank.py), so
    ``invoke`` must never let a post-dispatch sync failure reach this
    site's retry (which would re-dispatch into the consumed buffer):
    models/pagerank.py fetches the delta through its own guarded site
    (``pagerank_delta_sync``) whose retries re-pull against live OUTPUT
    buffers, and an exhausted inner fetch is non-transient here — it
    walks the rungs, and a rung that cannot read the consumed carry
    raises onward until ``ResilienceExhausted`` hands the caller the
    latest checkpoint.  This site's own transient failures (chaos fires
    at attempt start, before dispatch) still retry with the carry
    intact.

    A device loss surfacing inside the CHECKPOINT pull (the ISSUE 9
    carried-forward gap: the live carry died with the device, so
    ``extract_np`` cannot read it) walks the same ``elastic_rebuild``
    rung with a zero-iteration segment: the rung salvages the newest
    snapshot, rebuilds, re-runs only the uncommitted span, and the
    checkpoint is then written from the rebuilt state.

    Checkpoints are tagged with the segment's ``extra_metrics`` (the
    sharded runners put ``devices=N`` there), so a snapshot records which
    mesh shape wrote it — while staying readable across shrinks, because
    the payload is always the logical ``n`` ranks.

    Returns ``(ranks_dev, done, last_delta)``.
    """
    segment = (
        cfg.checkpoint_every
        if (cfg.checkpoint_every > 0 and cfg.tol == 0.0 and segments_allowed)
        else cfg.iterations - start_iter
    )
    # GRAFT_SYNC_DEADLINE_S guards *host syncs*, whose healthy duration is
    # bounded; a compiled segment's legitimate runtime scales with its
    # iteration count, so inheriting the sync deadline here would kill
    # healthy long segments.  The dispatch site gets its own knob
    # (GRAFT_STEP_DEADLINE_S, default 0 = no watchdog).
    policy = dataclasses.replace(
        rx.RetryPolicy.from_env(),
        deadline_s=float(os.environ.get("GRAFT_STEP_DEADLINE_S", 0.0)),
    )
    runners: dict[int, Callable] = {}
    cpu_invokes: dict[int, Callable] = {}
    done = start_iter
    last_delta = float("inf")

    def adopt(res: ElasticResult) -> None:
        # swap this loop onto the rebuilt execution context
        nonlocal make_runner, invoke, extract_np, extra_metrics
        make_runner, invoke, extract_np = (
            res.make_runner, res.invoke, res.extract_np
        )
        extra_metrics = {**(extra_metrics or {}), **res.metrics_extra}
        runners.clear()  # every cached program targeted the old mesh
        cpu_invokes.clear()

    while done < cfg.iterations:
        todo = min(segment, cfg.iterations - done)
        seg_cfg = dataclasses.replace(
            cfg, iterations=todo, checkpoint_every=0, checkpoint_dir=None
        )
        if todo not in runners:
            runners[todo] = make_runner(seg_cfg)
        rungs: list = []
        if elastic_rebuild is not None:
            def elastic_rung(exc, seg_cfg=seg_cfg, rd=ranks_dev):
                # salvage + shrink + rerun happen in the handler; here we
                # only swap this loop onto the rebuilt execution context
                res: ElasticResult = elastic_rebuild(exc, rd, done, seg_cfg)
                adopt(res)
                return res.ranks_dev, res.iters, res.delta

            rungs.append((None, elastic_rung))
        if make_cpu_invoke is not None:
            def cpu_rung(_exc, todo=todo, seg_cfg=seg_cfg, rd=ranks_dev):
                if todo not in cpu_invokes:
                    cpu_invokes[todo] = make_cpu_invoke(seg_cfg)
                return cpu_invokes[todo](rd)

            rungs.append(("cpu", cpu_rung))
        with Timer() as t, obs.span(f"{site_prefix}.segment",
                                    start=done, todo=todo):
            ranks_dev, iters, delta = rx.run_guarded(
                lambda r=runners[todo], rd=ranks_dev: invoke(r, rd),
                site=f"{site_prefix}_step", policy=policy, metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir, fallbacks=rungs,
            )
        done += int(iters)
        last_delta = float(delta)
        obs.histogram(f"{site_prefix}.segment_secs", t.elapsed)
        metrics.record(
            iter=done,
            l1_delta=last_delta,
            secs=t.elapsed,
            iters_per_sec=int(iters) / t.elapsed if t.elapsed > 0 else float("inf"),
            **(extra_metrics or {}),
        )
        if cfg.checkpoint_every > 0 and cfg.checkpoint_dir and done < cfg.iterations:
            with obs.span(f"{site_prefix}.checkpoint", iter=done):
                try:
                    payload = extract_np(ranks_dev)
                except Exception as exc:
                    # Device loss first surfacing at the checkpoint pull
                    # (ISSUE 9 carried-forward gap): the live carry is
                    # gone, so walk the same elastic salvage rung the
                    # segment dispatch uses — zero-iteration segment: the
                    # rung re-runs only the uncommitted span from the
                    # newest snapshot — and snapshot the rebuilt state.
                    if (elastic_rebuild is None
                            or elastic.unwrap_device_loss(exc) is None):
                        raise
                    res = elastic_rebuild(
                        exc, ranks_dev,
                        done, dataclasses.replace(seg_cfg, iterations=0),
                    )
                    adopt(res)
                    ranks_dev = res.ranks_dev
                    done += int(res.iters)  # 0 when salvage was exact
                    payload = extract_np(ranks_dev)
                path = ckpt.save_checkpoint(
                    cfg.checkpoint_dir, done,
                    {"ranks": payload}, cfg.config_hash(),
                    extra=dict(extra_metrics or {}),
                )
            metrics.record(event="checkpoint", path=path, iter=done)
        if cfg.tol > 0.0:
            # the while_loop runner handled tolerance in-program; one
            # segment is the whole run
            break

    metrics.scalar("iterations", done)
    metrics.scalar("l1_delta", last_delta)
    return ranks_dev, done, last_delta
