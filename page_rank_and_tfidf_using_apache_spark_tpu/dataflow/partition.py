"""``PartitionedArray`` — the dataflow core's partitioned-collection type.

The RDD analog (SURVEY.md L3 ``partitionBy``): ONE logical global array
plus the layout bookkeeping that maps it onto devices — padded length,
the global-id → padded-slot relabeling a partition strategy chose
(``parallel.pagerank_sharded.plan_partition``), and the mesh sharding the
device value carries.  Callers program against the logical view; the
padding/relabeling round-trip lives here once instead of inside each
runner (``_ShardedExec.put_ranks`` / ``extract_np`` are thin calls now).

The host→device direction pads and places; the device→host direction is
a *guarded* pull (resilience executor: retry / sync deadline /
degradation ladder) returning the logical array — so every workload that
states its results through a ``PartitionedArray`` inherits the repo's
host-sync discipline for free.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx


@dataclasses.dataclass(frozen=True)
class PartitionedArray:
    """A logical [n] array laid out as a padded (optionally sharded)
    device value of length ``n_pad``, with ``node_map[global_id] ->
    padded slot``.  ``sharding=None`` is the single-chip identity layout
    (n_pad == n, map == arange)."""

    n: int
    n_pad: int
    node_map: np.ndarray  # int64 [n]: global id -> padded slot
    value: Any = None  # device array [n_pad] (None until .put)
    sharding: Any = None  # jax.sharding.NamedSharding | None

    @classmethod
    def identity(cls, n: int) -> "PartitionedArray":
        """Single-chip layout: no padding, no relabeling."""
        return cls(n=n, n_pad=n, node_map=np.arange(n, dtype=np.int64))

    @classmethod
    def from_plan(cls, n: int, n_pad: int, node_map: np.ndarray,
                  sharding: Any = None) -> "PartitionedArray":
        """Layout from a partition plan's bookkeeping (the sharded
        runners pass ``ShardedGraph.n/n_pad/node_map`` + their state
        sharding)."""
        return cls(n=n, n_pad=n_pad, node_map=node_map, sharding=sharding)

    def put(self, global_np: np.ndarray, dtype=None) -> "PartitionedArray":
        """Pad + relabel + device_put a logical [n] host array; returns a
        new PartitionedArray holding the device value."""
        import jax

        dtype = dtype or global_np.dtype
        if self.n_pad == self.n and self.node_map.shape[0] == self.n and (
            self.node_map == np.arange(self.n)
        ).all():
            padded = np.asarray(global_np, dtype)
        else:
            padded = np.zeros(self.n_pad, dtype)
            padded[self.node_map] = global_np
        dev = (jax.device_put(padded, self.sharding)
               if self.sharding is not None else jax.device_put(padded))
        return dataclasses.replace(self, value=dev)

    def with_value(self, value: Any) -> "PartitionedArray":
        """The same layout around a new device value (a fixpoint's output
        carry keeps the input's partition plan)."""
        return dataclasses.replace(self, value=value)

    def pull(self, *, site: str = "partitioned_pull", metrics=None,
             checkpoint_dir: str | None = None) -> np.ndarray:
        """Guarded device→host pull of the LOGICAL array: one batched
        transfer through the resilience executor, then the node_map
        inverse on host."""
        if self.value is None:
            raise ValueError("PartitionedArray holds no device value")
        with obs.span("dataflow.pull", site=site, n=self.n):
            padded = rx.device_get(
                self.value, site=site, metrics=metrics,
                checkpoint_dir=checkpoint_dir,
            )
        return padded[self.node_map]


@dataclasses.dataclass(frozen=True)
class OwnedArray:
    """The owned-slice partitioned-collection layout (ISSUE 15): ONE
    logical [n] array split into a device-SHARDED padded tail (each shard
    holds only its owned block — nothing is replicated O(n)) plus a small
    REPLICATED hub-head mini-vector, mirroring
    ``ops.boundary.OwnedShard``'s node split.

    Same contract as :class:`PartitionedArray` — callers program against
    the logical view; host→device pads/places both components, and the
    device→host direction is one *guarded* batched pull (retry/deadline/
    ladder via the resilience executor) — so sharded PageRank, HITS and
    connected components on owned slices all inherit the host-sync
    discipline from this one class."""

    n: int
    n_pad: int  # d * block (tail layout width)
    h: int  # real head size
    h_pad: int
    tail_map: np.ndarray  # int64 [n]: global id -> padded tail slot; -1 head
    head_ids: np.ndarray  # int64 [H] ascending global ids
    tail: Any = None  # device array [n_pad], sharded along the mesh axis
    head: Any = None  # device array [h_pad], replicated
    tail_sharding: Any = None
    head_sharding: Any = None

    @classmethod
    def from_shard(cls, shard, *, tail_sharding: Any = None,
                   head_sharding: Any = None) -> "OwnedArray":
        """Layout view over a materialized ``ops.boundary.OwnedShard``."""
        return cls(
            n=shard.n, n_pad=shard.n_pad, h=shard.h, h_pad=shard.h_pad,
            tail_map=shard.tail_map, head_ids=shard.head_ids,
            tail_sharding=tail_sharding, head_sharding=head_sharding,
        )

    def put(self, global_np: np.ndarray, dtype=None) -> "OwnedArray":
        """Pad + split + device_put a logical [n] host array into the
        (sharded tail, replicated head) pair.  The split itself is
        ``ops.boundary.split_global`` — ONE implementation of the
        tail_map/head reassembly serves host planning and this layer."""
        import jax

        from page_rank_and_tfidf_using_apache_spark_tpu.ops import (
            boundary as ob,
        )

        dtype = dtype or global_np.dtype
        tail_np, head_np = ob.split_global(self, global_np, dtype)
        tail = (jax.device_put(tail_np, self.tail_sharding)
                if self.tail_sharding is not None
                else jax.device_put(tail_np))
        head = (jax.device_put(head_np, self.head_sharding)
                if self.head_sharding is not None
                else jax.device_put(head_np))
        return dataclasses.replace(self, tail=tail, head=head)

    def with_value(self, tail: Any, head: Any) -> "OwnedArray":
        """The same layout around a fixpoint's output carry components."""
        return dataclasses.replace(self, tail=tail, head=head)

    def pull(self, *, site: str = "partitioned_pull", metrics=None,
             checkpoint_dir: str | None = None) -> np.ndarray:
        """Guarded boundary-aware pull: ONE batched transfer for both
        components, then the tail_map/head reassembly on host."""
        from page_rank_and_tfidf_using_apache_spark_tpu.ops import (
            boundary as ob,
        )

        if self.tail is None or self.head is None:
            raise ValueError("OwnedArray holds no device value")
        with obs.span("dataflow.pull", site=site, n=self.n, owned=True):
            tail_np, head_np = rx.device_get(
                (self.tail, self.head), site=site, metrics=metrics,
                checkpoint_dir=checkpoint_dir,
            )
        return ob.merge_global(self, tail_np, head_np)
