"""Batched personalized PageRank — query-dependent teleport vectors along
a new vmap axis (ISSUE 9 workload 1; ROADMAP "personalized / weighted
PageRank ... batched along a new vmap axis").

The single-query path has existed since the seed (``PageRankConfig
.personalize`` → a concentrated restart vector), but it prices one query
at one full power iteration.  The serving-shaped workload is a *batch*
of queries (one personalization set per user/session) over ONE device-
resident graph: here the whole batch rides a ``jax.vmap`` axis over the
same :func:`ops.pagerank.pagerank_step` — the graph arrays are closed
over un-batched (broadcast, not copied), only the ``[B, n]`` rank carry
and ``[B, n]`` teleport matrix carry the query axis — and the fixpoint
is ONE compiled :func:`dataflow.fixpoint.iterate` loop whose convergence
gauge is the *worst* query's L1 delta, so the batch stops when every
query has.

Marginal-cost receipts: this module contains no shuffle, no scatter
strategy, no checkpoint/elastic/obs wiring of its own — the SpMV comes
from the shared impls (``cfg.spmv_impl``, including the degree-aware
hybrid layout), the host loop from ``dataflow.fixpoint.run_segments``
(checkpoints + retry + CPU degradation attached there, once), and
``bench.py --workloads`` records ``ppr_batch_queries_per_sec`` over it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.models import driver
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import put_graph_for
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.utils import config
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    RankInit,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def make_ppr_batch_runner(n: int, cfg: PageRankConfig):
    """Compile the batched-fixpoint loop: ``run(dg, ranks0 [B, n],
    e_batch [B, n]) -> (ranks [B, n], iters, delta)``.

    The ``[B, n]`` rank carry is **donated** (argnum 1), same contract as
    the single-query runner; ``delta`` is the max-over-queries L1 step
    delta, so a tolerance run ends only when the slowest query converged.
    One compile serves every batch of the same B (the batch axis is a
    shape, not a program).
    """
    import jax
    import jax.numpy as jnp

    damping = cfg.damping
    impl = cfg.spmv_impl
    dangling = cfg.dangling
    total_mass = float(n) if cfg.init is RankInit.ONE else 1.0

    @functools.partial(jax.jit, donate_argnums=(1,))
    def run(dg: ops.DeviceGraph, ranks0: jax.Array, e_batch: jax.Array):
        step_one = jax.vmap(
            lambda r, e: ops.pagerank_step(
                r, dg, e, n=n, damping=damping, dangling=dangling,
                total_mass=total_mass, impl=impl,
            )
        )
        return dflow.iterate(
            lambda rb: step_one(rb, e_batch), ranks0,
            iterations=cfg.iterations, tol=cfg.tol,
            delta_fn=lambda new, old: jnp.max(
                jnp.sum(jnp.abs(new - old), axis=1)
            ),
        )

    return run


def restart_batch(
    graph: Graph, cfg: PageRankConfig, queries: Sequence[Sequence[int]]
) -> np.ndarray:
    """[B, n] teleport matrix: one personalized restart vector per query
    (original node ids, resolved through the same compaction mapping the
    single-query path uses)."""
    rows = []
    for q in queries:
        q_cfg = driver.resolve_personalize(
            graph, dataclasses.replace(cfg, personalize=tuple(int(x) for x in q))
        )
        rows.append(ops.restart_vector(graph.n_nodes, q_cfg))
    return np.stack(rows)


@dataclasses.dataclass(frozen=True)
class PprBatchResult:
    ranks: np.ndarray  # f[B, n_nodes]
    iterations: int
    l1_delta: float  # worst query's final L1 step delta
    metrics: MetricsRecorder


def run_ppr_batch(
    graph: Graph,
    cfg: PageRankConfig,
    queries: Sequence[Sequence[int]],
    *,
    metrics: MetricsRecorder | None = None,
) -> PprBatchResult:
    """Run one batch of personalized PageRank queries to convergence.

    ``cfg.personalize`` must stay None — the per-query sets arrive in
    ``queries`` (original node ids).  Checkpointing/segments, retries and
    the CPU degradation rung all come from the shared dataflow fixpoint
    driver; the checkpoint payload is the ``[B, n]`` rank matrix.
    """
    config.ensure_dtype_support(cfg.dtype)
    if cfg.personalize is not None:
        raise ValueError("run_ppr_batch takes queries=, not cfg.personalize")
    if cfg.spark_exact:
        raise ValueError("spark_exact cannot be personalized")
    if not queries:
        raise ValueError("need at least one personalization query")
    metrics = metrics or MetricsRecorder()
    import jax

    n = graph.n_nodes
    e_host = restart_batch(graph, cfg, queries)  # host copy: salvage source
    b = len(queries)
    metrics.record(event="ppr_batch", queries=b, nodes=n)

    # The whole host loop — guarded delta sync, checkpoint segments, CPU
    # degradation and the elastic salvage rung — is the shared dataflow
    # driver; this workload only supplies its operands and call shape.
    ranks_np, done, last_delta = dflow.run_single_chip_fixpoint(
        cfg, metrics, site_prefix="ppr",
        init_state=lambda: np.broadcast_to(
            ops.init_ranks(n, cfg), (b, n)
        ).copy(),
        make_runner=lambda seg_cfg: make_ppr_batch_runner(n, seg_cfg),
        build_operands=lambda: (
            put_graph_for(graph, cfg), jax.device_put(e_host)
        ),
        call=lambda runner, ops_t, rd: runner(ops_t[0], rd, ops_t[1]),
    )
    return PprBatchResult(ranks=ranks_np, iterations=done,
                          l1_delta=last_delta, metrics=metrics)
