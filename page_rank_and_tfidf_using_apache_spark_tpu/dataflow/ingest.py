"""``chunked_ingest`` — the dataflow core's bounded-source ingest primitive.

Spark correspondence: reading a partitioned input (``textFile`` →
per-partition iterator chains) under a driver that tracks progress.  The
TPU-native shape (SURVEY.md §5.7): a bounded host source feeding
fixed-capacity padded device chunks through a once-compiled kernel, with
a donated device-resident carry, bounded in-flight launches, and commit
points (checkpoints) that only ever snapshot fully-drained state.

This module owns the three pieces every ingest path shares — the
:func:`grow_chunk_cap` fixed-shape padding policy (moved here from
``models/tfidf.py``, which re-exports it; the serving micro-batcher rides
the same policy at ``min_bits=0``), the :func:`prefetched` background-
thread source buffer, and the :func:`chunked_ingest` pipeline driver —
so the streaming TF-IDF path in ``models/tfidf.py`` is now a thin
program over this primitive (launch/drain/commit closures only), and the
next chunked workload starts from the same wiring instead of copying the
deque discipline.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Iterable, Iterator

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def grow_chunk_cap(
    need: int, cap: int, metrics: MetricsRecorder, *, min_bits: int = 10,
    **context
) -> tuple[int, bool]:
    """Fixed-shape capacity policy, shared by the streaming/sharded ingest
    paths AND the serving micro-batcher: power-of-two start (at least
    ``2**min_bits`` — the ingest default of 10 keeps token chunks
    kernel-sized; the serving batcher passes 0 so a batch of 3 pads to 4,
    not 1024), doubling bumps (each bump is a logged recompile —
    SURVEY.md §7 'fixed shapes under jit').  Returns (cap, changed)."""
    changed = False
    if cap <= 0:
        cap = 1 << max(min_bits, int(np.ceil(np.log2(max(need, 1)))))
        changed = True
    while need > cap:
        cap *= 2
        changed = True
        metrics.record(event="chunk_cap_bump", cap=cap, **context)
    return cap, changed


_QUEUE_END = object()


def prefetched(source: Iterator, depth: int) -> Iterator:
    """Run ``source`` on a background thread, buffering up to ``depth``
    items (SURVEY.md §5.7 double-buffered ingest).  Tokenizing is host
    C++/numpy that releases the GIL, so it genuinely overlaps the XLA chunk
    kernel.  Exceptions are forwarded and re-raised on the consumer side;
    if the consumer abandons the generator (exception or early close), the
    producer notices via a stop event and exits instead of blocking forever
    on a full queue."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in source:
                if not put(item):
                    return
        except BaseException as exc:  # noqa: BLE001 — forwarded to consumer
            put(exc)
        else:
            put(_QUEUE_END)

    thread = threading.Thread(target=producer, name="ingest-source",
                              daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _QUEUE_END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        thread.join()


def chunked_ingest(
    source: Iterable,
    *,
    launch: Callable,
    drain: Callable,
    commit: Callable[[], None],
    depth: int = 0,
    checkpoint_due: Callable[[], bool] | None = None,
    save_checkpoint: Callable[[], None] | None = None,
    prefetch_source: bool = True,
) -> None:
    """Drive a bounded source through a launch/drain pipeline with commit
    points — the host half of the streaming ingest, shared wiring for the
    resilience/checkpoint discipline:

    - ``launch(item)`` dispatches one chunk (async) and returns an
      in-flight record; up to ``depth`` launches stay in flight before
      the oldest is drained (``depth == 0`` is fully serial).
    - ``drain(record)`` completes one launch (the guarded host pull —
      sites/spans belong to the caller's closure).
    - ``commit()`` pulls carry state the kernel accumulates on device
      (e.g. the donated DF carry).  Called only when NOTHING is in
      flight — a snapshot must never hold contributions from chunks it
      does not record as ingested — and once at the end.
    - ``checkpoint_due()`` / ``save_checkpoint()``: when due, the
      pipeline drains everything in flight, commits, then snapshots.

    With ``prefetch_source=True`` and ``depth > 0`` the source iterator
    additionally runs on a background thread (:func:`prefetched`), so
    host-side chunk preparation overlaps device compute.
    """
    depth = max(int(depth), 0)
    it: Iterable = source
    if prefetch_source and depth > 0:
        it = prefetched(iter(source), depth)

    inflight: collections.deque = collections.deque()

    def maybe_checkpoint() -> None:
        if checkpoint_due is None or save_checkpoint is None:
            return
        if not checkpoint_due():
            return
        while inflight:  # drain to the commit point
            drain(inflight.popleft())
        commit()
        save_checkpoint()

    for item in it:
        inflight.append(launch(item))
        while len(inflight) > depth:
            drain(inflight.popleft())
        maybe_checkpoint()
    while inflight:
        drain(inflight.popleft())
        maybe_checkpoint()
    commit()
