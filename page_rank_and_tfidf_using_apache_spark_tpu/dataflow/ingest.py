"""``chunked_ingest`` — the dataflow core's staged, double-buffered
bounded-source ingest pipeline.

Spark correspondence: a ``spark.streaming`` receiver chain — a receiver
thread buffering input blocks, the block manager shipping them to
executors, and the driver scheduling micro-batches over what has landed —
under a driver that tracks progress.  The TPU-native shape (SURVEY.md
§5.7): a bounded host source feeding fixed-capacity padded device chunks
through a once-compiled kernel, with a donated device-resident carry,
bounded in-flight launches, and commit points (checkpoints) that only
ever snapshot fully-drained state.

The pipeline is genuinely staged (ISSUE 10):

    source ──► tokenize ──► H2D staging ──► compute ──► drain ─► commit
               (``prefetch``   (``pipeline_depth``  (``prefetch``   (barrier)
                thread,         transfer thread,     in-flight
                bounded queue)  bounded queue)       launches)

- the **tokenize** stage is the caller's source iterator run on a
  background thread (:class:`Prefetched`) buffering up to ``prefetch``
  chunks;
- the **H2D staging** stage runs the caller's ``stage(item)`` closure —
  which issues ``jax.device_put`` through :func:`staged_put` (chaos/retry
  site ``ingest_h2d_put``) — on a transfer thread, holding at most
  ``pipeline_depth`` staged chunks of device memory and exerting
  backpressure on the tokenize queue;
- the **compute** stage (``launch``) consumes pre-staged device buffers
  only; up to ``prefetch`` launches stay in flight before the oldest is
  drained;
- **commit points** run behind a drain-before-commit barrier
  (:func:`fixpoint.commit_barrier`), so checkpoints only ever snapshot
  fully-drained state and the donated carry is pulled with nothing in
  flight.

Every stage opens its own obs span (``ingest.tokenize`` / ``ingest.h2d``
/ ``ingest.compute``), and one ``ingest_overlap`` event plus an
``h2d_overlap_frac`` gauge — the fraction of H2D staging wall time spent
while chunk compute was in flight — are published per run, so
trace_report can prove where the overlap lands from the artifact alone.

Fault model: the two pipeline-internal sites (``ingest_h2d_put`` on the
transfer thread, ``ingest_h2d_wait`` on the consumer side) retry
transient faults like every guarded site but propagate persistent ones
RAW (``resilience.executor.retry_transient``) to the single recovery
point here: on failure the pipeline tears its threads down, collects
every item that was staged/launched but never drained (plus the
prefetchers' unconsumed buffers — nothing is ever silently dropped), and
hands ``(exc, remaining, where)`` to the caller's ``recover`` hook.  The
hook acknowledges the loss (elastic shrink for sharded meshes, CPU
salvage for single-chip carries) and the pipeline restarts over the
remaining items — committed chunks are never reprocessed, and the
reprocessed span is byte-identical because it replays the same host
arrays in the same order.

This module owns :func:`grow_chunk_cap` (fixed-shape padding policy —
``models/tfidf.py`` re-exports it; the serving micro-batcher rides it at
``min_bits=0``), :func:`pack_doc_chunks` (the re-batching stage that
fills compiled caps so padding stops taxing compute), the
:class:`Prefetched` bounded background buffer, and the
:func:`chunked_ingest` driver — so the streaming TF-IDF path in
``models/tfidf.py`` and the sharded path in ``parallel/tfidf_sharded.py``
are thin programs over one primitive.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import re
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import IngestConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder

# Chaos/retry sites of the pipeline's own stages (resilience/chaos.py
# grammar: e.g. GRAFT_CHAOS="ingest_h2d_put:device_lost@dev:1").
H2D_PUT_SITE = "ingest_h2d_put"
H2D_WAIT_SITE = "ingest_h2d_wait"

# A recovery loop that cannot make progress must terminate: every
# legitimate recovery acknowledges a loss or shrinks the mesh, and no
# real topology survives this many independent device losses.
_MAX_RECOVERIES = 16


def grow_chunk_cap(
    need: int, cap: int, metrics: MetricsRecorder, *, min_bits: int = 10,
    **context
) -> tuple[int, bool]:
    """Fixed-shape capacity policy, shared by the streaming/sharded ingest
    paths AND the serving micro-batcher: power-of-two start (at least
    ``2**min_bits`` — the ingest default of 10 keeps token chunks
    kernel-sized; the serving batcher passes 0 so a batch of 3 pads to 4,
    not 1024), doubling bumps (each bump is a logged recompile —
    SURVEY.md §7 'fixed shapes under jit').  Returns (cap, changed)."""
    changed = False
    if cap <= 0:
        cap = 1 << max(min_bits, int(np.ceil(np.log2(max(need, 1)))))
        changed = True
    while need > cap:
        cap *= 2
        changed = True
        metrics.record(event="chunk_cap_bump", cap=cap, **context)
    return cap, changed


_ALNUM_RUN = re.compile(r"[A-Za-z0-9]+")


def estimate_tokens(doc: str) -> int:
    """Alphanumeric-run count — the exact split rule of the default
    tokenizer (``io.text._TOKEN_RE``), so this is a true upper bound for
    unigram vocabularies: ``min_token_len`` can only drop runs.  Cheap
    enough to run over the raw corpus before tokenization (no string
    allocation per token)."""
    return sum(1 for _ in _ALNUM_RUN.finditer(doc))


def ngram_estimator(ngram: int) -> Callable[[str], int]:
    """Token-count upper bound matching ``io.text.add_ngrams``: ``t``
    unigram runs expand to ``t + (t-1) + ... + (t-n+1)`` tokens.  Still an
    upper bound — ``min_token_len`` filtering happens before the ngram
    join, so it can only shrink both terms."""
    if ngram <= 1:
        return estimate_tokens

    def estimate(doc: str) -> int:
        t = estimate_tokens(doc)
        return sum(max(t - k + 1, 0) for k in range(1, ngram + 1))

    return estimate


def pack_doc_chunks(
    doc_chunks: Iterable[Sequence[str]],
    target_tokens: int,
    *,
    estimate: Callable[[str], int] = estimate_tokens,
) -> Iterator[list[str]]:
    """The re-batching stage of the ingest pipeline: regroup documents so
    each emitted chunk carries ~``target_tokens`` tokens (documents never
    split — per-chunk run-length DF stays exact), turning a badly sized
    source chunking into cap-filling chunks.

    Why it matters: the chunk kernel compiles for a fixed power-of-two
    capacity and sorts/reduces the PADDED arrays, so a stream of
    one-third-full chunks pays ~3x the compute of the batch pipeline —
    exactly the BENCH_r07 streaming-vs-batch gap (92k-token chunks padded
    to a 2^18 cap).  Packing fills the cap to within one document.

    Deterministic for a given source + target, so checkpoint chunk
    indices stay valid across resume runs (``chunk_index`` counts PACKED
    chunks; resume must re-pack with the same target).
    """
    target = max(int(target_tokens), 1)
    cur: list[str] = []
    est = 0
    for chunk in doc_chunks:
        for doc in chunk:
            e = max(int(estimate(doc)), 1)
            if cur and est + e > target:
                yield cur
                cur, est = [], 0
            cur.append(doc)
            est += e
    if cur:
        yield cur


def staged_put(put: Callable[[], Any], *,
               metrics: MetricsRecorder | None = None) -> Any:
    """Issue one H2D transfer under the staging discipline: the
    ``ingest_h2d_put`` chaos/retry site — transient faults retried with
    backoff, persistent faults (device loss) propagated RAW to the
    pipeline's recovery point (``chunked_ingest(recover=...)``), which
    owns the shrink/salvage.  Every per-chunk ``jax.device_put`` in an
    ingest loop must route through this (lint rule
    ``sync-put-in-ingest-loop``)."""
    return rx.retry_transient(put, site=H2D_PUT_SITE, metrics=metrics)


# ------------------------------------------------------------- prefetcher


class _Item:
    __slots__ = ("item",)

    def __init__(self, item):
        self.item = item


class _Raised:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class _End:
    pass


_END = _End()


class Prefetched:
    """Bounded background-thread buffer over an iterator, with an explicit
    poison/close protocol (ISSUE 10 satellite):

    - up to ``depth`` items are produced ahead on a daemon thread; a full
      queue backpressures the producer;
    - a producer exception travels through the queue and re-raises on the
      consumer side WITH the original traceback (the exception object's
      ``__traceback__`` still points at the producer frames);
    - :meth:`close` shuts the producer down promptly even when it is
      blocked on a full queue, and preserves every item the consumer
      never saw: :meth:`leftover` (+ the still-held ``source`` iterator)
      lets a recovery path resume the stream with zero loss — an item the
      producer had in hand when the close hit is parked, never dropped.

    Abandoning the iterator without ``close()`` (the legacy generator
    wrapper closes in its ``finally``) leaves only a daemon thread that
    exits at its next queue poll.
    """

    def __init__(self, source: Iterator, depth: int, *,
                 name: str = "ingest-source"):
        self.source = source
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self._orphans: list = []
        self._leftover: list = []
        self._raised: list = []
        self._closed = False
        self._finished = False
        self.thread = threading.Thread(target=self._produce, name=name,
                                       daemon=True)
        self.thread.start()

    def _put(self, env) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(env, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for item in self.source:
                if not self._put(_Item(item)):
                    # close() hit while this item was in hand: park it for
                    # leftover() — a recovery path must not lose it.
                    # Only this thread writes the parking lists, and
                    # close() joins before anyone reads them.
                    self._orphans.append(item)  # graftlint: disable=unsynced-thread-state (producer-only write; close() joins before any read)
                    return
        except BaseException as exc:  # noqa: BLE001 — forwarded to consumer
            if not self._put(_Raised(exc)):
                # close() hit while the exception was in hand: park it —
                # a _StageFailure carries the casualty item, which a
                # recovery path must still salvage (raised())
                self._raised.append(exc)  # graftlint: disable=unsynced-thread-state (producer-only write; close() joins before any read)
        else:
            self._put(_END)

    def __iter__(self) -> "Prefetched":
        return self

    def __next__(self):
        if self._closed or self._finished:
            raise StopIteration
        env = self._q.get()
        if env is _END:
            self._finished = True
            self.thread.join()
            raise StopIteration
        if isinstance(env, _Raised):
            self._finished = True
            self.thread.join()
            raise env.exc.with_traceback(env.exc.__traceback__)
        return env.item

    def close(self) -> None:
        """Poison the producer and reap it, preserving unconsumed items
        (drains the queue so a producer blocked on a full one unblocks
        immediately instead of timing out its poll)."""
        if self._closed or self._finished:
            self._closed = True
            return
        self._closed = True
        self._stop.set()
        left: list = []
        while True:
            try:
                env = self._q.get(timeout=0.05)
            except queue.Empty:
                if not self.thread.is_alive():
                    break
                continue
            if isinstance(env, _Item):
                left.append(env.item)
            elif isinstance(env, _Raised):
                self._raised.append(env.exc)
        self.thread.join()
        while True:  # final sweep: a put may have landed before the exit
            try:
                env = self._q.get_nowait()
            except queue.Empty:
                break
            if isinstance(env, _Item):
                left.append(env.item)
            elif isinstance(env, _Raised):
                self._raised.append(env.exc)
        left.extend(self._orphans)
        self._leftover = left

    def leftover(self) -> list:
        """Items produced but never consumed, in stream order — valid
        after :meth:`close`.  ``source`` may still hold more."""
        return list(self._leftover)

    def raised(self) -> list:
        """Producer exceptions swept up by :meth:`close` before the
        consumer ever saw them (the consumer died first) — a recovery
        path must inspect these, or an item a failing producer had in
        hand would vanish with its unread exception."""
        return list(self._raised)


def prefetched(source: Iterator, depth: int) -> Iterator:
    """Legacy generator wrapper over :class:`Prefetched` (same contract:
    background production up to ``depth`` ahead, producer exceptions
    re-raised consumer-side, clean producer shutdown when the consumer
    abandons the generator early)."""
    pf = Prefetched(iter(source), depth)
    try:
        for item in pf:
            yield item
    finally:
        pf.close()


# ---------------------------------------------------------------- pipeline


class _IteratorRaised(BaseException):
    """Carrier for an exception raised from INSIDE the staged iterator at
    the wait site.  BaseException with an empty message, so the retry
    machinery can neither catch it (``retry_transient`` retries only
    ``Exception``) nor marker-match the inner error as transient — the
    pull of a stateful iterator must never be re-invoked after it raised.
    Unwrapped immediately at the call site."""

    def __init__(self, exc: BaseException):
        super().__init__()
        self.exc = exc


class _StageFailure(RuntimeError):
    """The H2D staging stage failed for ``item`` (stage thread side):
    items staged before it are buffered/launched, items after it never
    left the source."""

    def __init__(self, item, cause: BaseException):
        super().__init__(str(cause))
        self.item = item
        self.cause = cause


class _LaunchFailure(RuntimeError):
    """``launch`` failed for ``item`` (main thread side): the item came
    off the staged queue BEFORE anything still buffered there."""

    def __init__(self, item, cause: BaseException):
        super().__init__(str(cause))
        self.item = item
        self.cause = cause


class _DrainFailure(RuntimeError):
    """``drain`` failed; the chunk being drained is still accounted in
    the in-flight deque (popped only on success)."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


def _merge_intervals(ivs: list) -> list:
    out: list = []
    for a, b in sorted(ivs):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def overlap_fraction(h2d: list, compute: list) -> float:
    """Fraction of total H2D staging wall time spent while chunk compute
    was in flight — the per-run gauge that proves (or disproves) the
    double-buffering.  0.0 with no staging time."""
    total = sum(b - a for a, b in h2d)
    if total <= 0:
        return 0.0
    merged = _merge_intervals(compute)
    ov = 0.0
    j = 0
    for a, b in sorted(h2d):
        while j < len(merged) and merged[j][1] <= a:
            j += 1
        k = j
        while k < len(merged) and merged[k][0] < b:
            lo, hi = max(a, merged[k][0]), min(b, merged[k][1])
            if lo < hi:
                ov += hi - lo
            k += 1
    return min(max(ov / total, 0.0), 1.0)


def chunked_ingest(
    source: Iterable,
    *,
    launch: Callable,
    drain: Callable,
    commit: Callable[[], None],
    depth: int = 0,
    checkpoint_due: Callable[[], bool] | None = None,
    save_checkpoint: Callable[[], None] | None = None,
    prefetch_source: bool = True,
    stage: Callable | None = None,
    # 0 is a semantic sentinel (inline staging, no transfer thread) — NOT
    # the tuned pipeline depth; callers pass the resolved knob explicitly
    # (or via ``ingest=``), so the ladder still reaches every real run
    pipeline_depth: int = 0,  # graftlint: disable=untuned-knob-read
    ingest: IngestConfig | None = None,
    recover: Callable | None = None,
    retain_until_commit: bool = False,
    metrics: MetricsRecorder | None = None,
) -> None:
    """Drive a bounded source through the staged launch/drain pipeline
    with commit points — the host half of the streaming ingest, shared
    wiring for the resilience/checkpoint discipline:

    - ``stage(item)`` (optional) runs the H2D staging stage: pad + issue
      ``jax.device_put`` (through :func:`staged_put`) and return a staged
      record.  With ``pipeline_depth > 0`` it runs on a transfer thread
      holding at most that many staged chunks of device memory (the
      double buffer); with 0 it runs inline.  Omitted, items flow to
      ``launch`` unstaged (legacy callers).
    - ``launch(staged)`` dispatches one chunk (async) against pre-staged
      device buffers and returns an in-flight record; up to ``depth``
      launches stay in flight before the oldest is drained (``depth ==
      0`` is fully serial).
    - ``drain(record)`` completes one launch (the guarded host pull —
      sites/spans belong to the caller's closure).
    - ``commit()`` pulls carry state the kernel accumulates on device
      (e.g. the donated DF carry).  Runs behind the drain-before-commit
      barrier (:func:`fixpoint.commit_barrier`) — a snapshot must never
      hold contributions from chunks it does not record as ingested —
      and once at the end.
    - ``checkpoint_due()`` / ``save_checkpoint()``: when due, the barrier
      drains everything in flight, commits, then snapshots.
    - ``recover(exc, remaining, where)`` (optional): the single recovery
      point for persistent faults anywhere in the pipeline.  By the time
      it runs the stage/tokenize threads are torn down and ``remaining``
      iterates every unprocessed item in stream order (staged, launched
      and buffered items are re-delivered from their retained host-side
      form — zero loss, zero double-commits).  ``where`` names the stage
      that failed (``"stage"`` / ``"wait"`` / ``"launch"`` / ``"drain"``).
      The hook re-raises faults it does not own, or acknowledges the loss
      (mesh shrink / CPU salvage), rebuilds device state, and returns the
      iterable to continue with (usually ``remaining``, possibly
      regrouped).  Without a hook, the fault propagates as-is.
    - ``retain_until_commit=True`` additionally retains every DRAINED
      item until the next commit barrier and re-delivers those too (ahead
      of everything else) on recovery.  For callers whose drain is not a
      full commit — single-chip streaming TF-IDF: a drained chunk's TF
      counts are on host but its DF contribution lives only in the
      donated device carry, which dies with the device — the recover
      hook must then roll its own state back to the last commit point so
      the replay cannot double-count.  Callers whose drain commits
      everything to host (the sharded path pulls its psum'd DF per
      super-chunk) leave this False: drained items are done.

    ``ingest=IngestConfig(...)`` sets ``depth`` (= ``prefetch``) and
    ``pipeline_depth`` in one bundle.  Per-stage obs spans
    (``ingest.tokenize`` / ``ingest.h2d`` / ``ingest.compute``), the
    ``ingest_overlap`` event and the ``h2d_overlap_frac`` gauge are
    published here so every caller gets the same accounting.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint

    if ingest is not None:
        depth = ingest.prefetch
        pipeline_depth = ingest.pipeline_depth
    depth = max(int(depth), 0)
    pipeline_depth = max(int(pipeline_depth), 0)

    tok_iv: list = []
    h2d_iv: list = []
    comp_iv: list = []
    inflight: collections.deque = collections.deque()  # (item, record, t0)
    drained: list = []  # items drained since the last commit barrier
    # (retained only under retain_until_commit, replayed on recovery)

    def spanned_source(it: Iterator) -> Iterator:
        # runs on whichever thread consumes it: the tokenize prefetch
        # thread when prefetch > 0, else the H2D/main thread
        while True:
            t0 = time.perf_counter()
            with obs.span("ingest.tokenize"):
                try:
                    item = next(it)
                except StopIteration:
                    return
            tok_iv.append((t0, time.perf_counter()))
            yield item

    def stage_wrap(item):
        t0 = time.perf_counter()
        try:
            with obs.span("ingest.h2d"):
                staged = stage(item)
        except BaseException as exc:
            raise _StageFailure(item, exc) from exc
        h2d_iv.append((t0, time.perf_counter()))
        return (item, staged)

    def drain_oldest() -> None:
        item, rec, t0 = inflight[0]
        try:
            with obs.span("ingest.compute"):
                drain(rec)
        except BaseException as exc:
            raise _DrainFailure(exc) from exc
        inflight.popleft()  # popped only on success: a failed drain's
        # chunk stays accounted as unprocessed for recovery
        if retain_until_commit:
            drained.append(item)
        comp_iv.append((t0, time.perf_counter()))

    def drain_all() -> None:
        while inflight:
            drain_oldest()

    def commit_and_release() -> None:
        # the barrier guarantees nothing is in flight here: once the
        # carry pull lands, the drained chunks are durably committed and
        # their retained host copies can go.  The commit-point event is
        # what downstream consumers key on — a delta-segment seal
        # (serving/segments.py) is exactly "everything up to this commit
        # is durable", so the trace shows when servable state existed.
        commit()
        obs.emit("ingest_commit", chunks=len(comp_iv),
                 retained=len(drained))
        drained.clear()

    def maybe_checkpoint() -> None:
        if checkpoint_due is None or save_checkpoint is None:
            return
        if not checkpoint_due():
            return
        fixpoint.commit_barrier(drain_all, commit_and_release,
                                save_checkpoint)

    # The wait site runs WITHOUT the sync watchdog: its pull is a local
    # thread handoff (queue read / inline stage), not a device sync —
    # every device-facing block that feeds it is already deadlined at the
    # put site on the thread that runs it.  A watchdog here would abandon
    # an attempt still blocked inside next() on the stateful staged
    # iterator and retry concurrently; whatever item the abandoned thread
    # then consumed would vanish from the committed output.
    wait_policy = dataclasses.replace(rx.RetryPolicy.from_env(),
                                      deadline_s=0.0)
    items: Iterator = iter(source)
    recoveries = 0
    while True:
        tok_pf: Prefetched | None = None
        stage_pf: Prefetched | None = None
        try:
            feed: Iterator = spanned_source(items)
            if prefetch_source and depth > 0:
                tok_pf = Prefetched(feed, depth)
                feed = tok_pf
            if stage is not None:
                staged_feed: Iterator = map(stage_wrap, feed)
                if pipeline_depth > 0:
                    stage_pf = Prefetched(staged_feed, pipeline_depth,
                                          name="ingest-h2d")
                    staged_feed = stage_pf

                def next_staged(sf=staged_feed):
                    # the consumer-side handoff from the staging stage:
                    # its own chaos/retry site, so faults on in-flight
                    # staged chunks are injectable from the waiting side.
                    # The chaos hook fires BEFORE the pull, so a retried
                    # transient injected fault never consumed an item —
                    # but an exception coming OUT of the iterator must
                    # propagate raw even when its message carries a
                    # transient marker: the iterator is stateful, and
                    # re-invoking next() would skip the failed item (or
                    # read _END off a finished Prefetched), silently
                    # dropping chunks from the committed output.
                    def pull():
                        try:
                            return next(sf, _END)
                        except BaseException as exc:
                            raise _IteratorRaised(exc)
                    try:
                        return rx.retry_transient(
                            pull, site=H2D_WAIT_SITE, metrics=metrics,
                            policy=wait_policy,
                        )
                    except _IteratorRaised as carrier:
                        raise carrier.exc
            else:
                plain = map(lambda it_: (it_, it_), feed)

                def next_staged(sf=plain):
                    return next(sf, _END)

            while True:
                env = next_staged()
                if env is _END:
                    break
                item, staged = env
                t0 = time.perf_counter()
                try:
                    rec = launch(staged)
                except BaseException as exc:
                    raise _LaunchFailure(item, exc) from exc
                inflight.append((item, rec, t0))
                while len(inflight) > depth:
                    drain_oldest()
                maybe_checkpoint()
            while inflight:
                drain_oldest()
                maybe_checkpoint()
            fixpoint.commit_barrier(drain_all, commit_and_release)
            break
        except BaseException as exc:  # noqa: BLE001 — dispatched below
            cause: BaseException = exc
            where = "drain"
            failed_early: list = []  # failed item ordered before buffers
            failed_late: list = []  # failed item ordered after buffers
            if isinstance(exc, _DrainFailure):
                cause, where = exc.cause, "drain"
            elif isinstance(exc, _LaunchFailure):
                cause, where = exc.cause, "launch"
                failed_early = [exc.item]
            elif isinstance(exc, _StageFailure):
                cause, where = exc.cause, "stage"
                failed_late = [exc.item]
            elif inflight or stage is not None:
                where = "drain" if inflight else "wait"
            # Tear the pipeline down FIRST: recovery must never race the
            # stage thread (a put onto a dying mesh) — and collect every
            # unprocessed item in stream order: drained-but-uncommitted
            # (when retained), launched-but-undrained, the launch
            # casualty, staged-but-unlaunched buffers, the stage
            # casualty, then unstaged tokenized buffers.
            replay = list(drained)
            drained.clear()
            pending = [it for (it, _rec, _t0) in inflight]
            inflight.clear()
            staged_left: list = []
            src_raised: list = []  # swept-up SOURCE/tokenize exceptions:
            # the stream is truncated past them, so replay must re-raise
            # them in stream position, never complete "successfully"
            if stage_pf is not None:
                stage_pf.close()
                staged_left = [it for (it, _st) in stage_pf.leftover()]
                # a stage failure the consumer never read (it died first,
                # e.g. at the wait site): the casualty item rides in the
                # swept-up exception — salvage it, in queue order (the
                # producer stops at its first failure, so it is last).
                # Anything else swept here propagated through the stage
                # thread FROM the source (stage_wrap wraps stage faults).
                for r_exc in stage_pf.raised():
                    if isinstance(r_exc, _StageFailure):
                        staged_left.append(r_exc.item)
                    else:
                        src_raised.append(r_exc)
            tok_left: list = []
            if tok_pf is not None:
                tok_pf.close()
                tok_left = tok_pf.leftover()
                src_raised.extend(tok_pf.raised())
            if recover is None:
                raise cause
            recoveries += 1
            if recoveries > _MAX_RECOVERIES:
                raise cause
            head = (replay + pending + failed_early + staged_left
                    + failed_late + tok_left)

            def chained(head=head, tail=items, swept=src_raised):
                yield from head
                if swept:
                    # the source raised before teardown and the consumer
                    # never saw it: past this point the stream does not
                    # exist, so it must fail here, not end
                    raise swept[0].with_traceback(swept[0].__traceback__)
                yield from tail

            items = iter(recover(cause, chained(), where))

    frac = overlap_fraction(h2d_iv, comp_iv)
    summary = {
        "h2d_overlap_frac": round(frac, 4),
        "tokenize_secs": round(sum(b - a for a, b in tok_iv), 4),
        "h2d_secs": round(sum(b - a for a, b in h2d_iv), 4),
        "compute_secs": round(sum(b - a for a, b in comp_iv), 4),
        "chunks": len(comp_iv),
        "depth": depth,
        "pipeline_depth": pipeline_depth,
    }
    obs.gauge("h2d_overlap_frac", frac)
    obs.emit("ingest_overlap", **summary)
    if metrics is not None:
        metrics.record(event="ingest_overlap", **summary)
