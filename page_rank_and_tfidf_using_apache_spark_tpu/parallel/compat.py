"""JAX version compatibility shims for the parallel layer.

The sharded runners are written against the modern top-level
``jax.shard_map`` API (``check_vma=`` kwarg).  Older jax releases (< 0.6)
only ship ``jax.experimental.shard_map.shard_map`` whose replication-check
kwarg is spelled ``check_rep``.  This module exports one ``shard_map``
callable with the modern signature on every supported jax.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax < 0.6: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map_experimental(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )


__all__ = ["shard_map"]
