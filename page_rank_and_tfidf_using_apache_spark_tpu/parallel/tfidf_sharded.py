"""Multi-chip TF-IDF: data-parallel chunk ingest with psum'd DF and a
replicated IDF broadcast.

Reference counterpart (SURVEY.md §2.2 R1–R3, BASELINE.json:11): Spark
splits the corpus into partitions, shuffles ((term, doc), 1) records for the
TF and DF passes, and torrent-broadcasts small tables.  Here each device
ingests its own fixed-shape token chunk (documents never span chunks, so
per-chunk run-length DF increments are exact), one ``psum`` over the mesh
combines the per-device DF vectors — the DF `reduceByKey` — and the
resulting IDF vector is *replicated* across chips, which is BASELINE.json:5's
"IDF broadcast across chips" realized as a sharding annotation instead of a
torrent protocol.

Shapes: a "super-chunk" is [D, cap] token arrays, one row per device;
compile happens once per (D, cap).

Since ISSUE 10 the host loop IS ``dataflow.ingest.chunked_ingest`` — the
same staged pipeline as single-chip streaming: a tokenize thread feeds
super-chunk groups, a transfer thread issues the **sharded puts** for
group N+1 (chaos/retry site ``ingest_h2d_put``) while group N computes,
holding at most ``cfg.pipeline_depth`` staged groups of device memory,
and the drain is the one guarded batched pull per super-chunk.  Device
loss anywhere in the pipeline reaches the single recovery point: the
committed ingest state is checkpointed, the mesh is rebuilt over the
survivors (``elastic.plan_shrink``), and the pipeline **re-slices the
in-flight staged groups over the shrunk mesh** by regrouping the host
corpora it retained — committed chunks are never reprocessed, and a
second loss inside the replay simply re-enters the same recovery point
(4 → 2 → 1 chaos-tested).
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator, Sequence

import jax
import numpy as np
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import ingest as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    IngestState,
    TfidfOutput,
    _tokenized_chunks,
    finalize_tfidf,
    grow_chunk_cap,
    resume_ingest,
    save_ingest_checkpoint,
)
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import collectives as coll
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    rebuild_mesh,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig, ensure_dtype_support
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def _publish_device_timings(arr, step: int) -> None:
    """Per-device shard-ready timings for the trace chunk timeline
    (ROADMAP hardening (d)): fence each device's shard of the tiny
    ``n_pairs`` vector and record when it became ready, measured from the
    call.  Shards are waited in device order, so entry ``i`` is an upper
    bound for a device that finished while an earlier one still ran — the
    straggler (the max) is exact, which is what load-balance debugging
    needs.  Best-effort telemetry: any fault here is left for the guarded
    batched pull that follows.  Runs ONLY under an active traced run —
    untraced ingest keeps the single batched pull as its only sync (on a
    tunnel-attached TPU each per-shard fence is a real host round-trip,
    and with no run the event would be discarded anyway)."""
    if obs.current_run() is None:
        return
    try:
        t0 = time.perf_counter()
        secs = []
        for s in arr.addressable_shards:
            s.data.block_until_ready()  # graftlint: disable=unguarded-host-sync,host-sync-in-loop (per-shard fence for telemetry only; the guarded batched pull right after owns retry/deadline/degradation)
            secs.append(round(time.perf_counter() - t0, 6))
        obs.emit("device_timing", site="tfidf_super_chunk", step=step,
                 devices=len(secs), secs=secs)
    except Exception:  # noqa: BLE001 — never let telemetry kill ingest
        pass


def make_sharded_counts_kernel(mesh: Mesh, vocab: int):
    """Compile: [D, cap] tokens → per-device counts + globally-psum'd DF."""
    axis = mesh.axis_names[0]

    def kernel(doc_ids, term_ids, valid):
        counts = ops.count_pairs(doc_ids[0], term_ids[0], token_valid=valid[0])
        df_local = ops.document_frequency(counts, vocab)
        df = coll.psum(df_local, axis)  # the DF reduceByKey, on ICI
        # re-add the device axis so out_specs can shard along it
        return (counts.doc[None], counts.term[None], counts.count[None],
                counts.n_pairs[None], counts.valid[None]), df

    esh = P(axis, None)
    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(esh, esh, esh),
            out_specs=(
                (esh, esh, esh, P(axis), esh),
                P(),  # DF replicated — the IDF broadcast target
            ),
            check_vma=False,
        )
    )


def run_tfidf_sharded(
    doc_chunks: Iterable[Sequence[str]],
    cfg: TfidfConfig,
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> TfidfOutput:
    """Sharded counterpart of models.tfidf.run_tfidf_streaming: consumes the
    same chunk iterator, ingesting D chunks per device step.  Checkpointing
    shares the streaming path's format (``cfg.checkpoint_every`` counts input
    *chunks*, not super-chunks, so a config moved between the two paths
    checkpoints at the same cadence) and ``resume=True`` skips the
    already-ingested prefix of the iterator."""
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, DATA_AXIS)
    d = int(mesh.devices.size)
    axis = mesh.axis_names[0]
    vocab = cfg.vocab_size
    dtype = cfg.dtype

    cap = cfg.chunk_tokens
    kernel = make_sharded_counts_kernel(mesh, vocab)
    esh = NamedSharding(mesh, P(axis, None))

    st = (resume_ingest(cfg, metrics) if resume
          else IngestState(df_total=np.zeros(vocab, dtype)))
    last_ckpt = st.chunk_index
    secs0 = st.ingest_secs
    run_started = time.perf_counter()
    step = 0

    if cfg.pack_target_tokens > 0:
        doc_chunks = dflow.pack_doc_chunks(
            doc_chunks, cfg.pack_target_tokens,
            estimate=dflow.ngram_estimator(cfg.ngram))
    chunk_source = _tokenized_chunks(doc_chunks, cfg, st.chunk_index,
                                     st.n_docs)

    def grouped(src: Iterator) -> Iterator[list[tio.TokenizedCorpus]]:
        # one pipeline item = one super-chunk group of <= d corpora; ``d``
        # is read per group, so after a shrink the tail arrives pre-sized
        # (in-flight old-width groups are regrouped by ``recover`` below)
        buf: list[tio.TokenizedCorpus] = []
        for _, corpus in src:
            buf.append(corpus)
            if len(buf) >= d:
                yield buf
                buf = []
        if buf:
            yield buf

    def stage_group(group: list[tio.TokenizedCorpus]):
        """H2D staging stage (transfer thread): build the [D, cap] host
        arrays for one super-chunk and issue the sharded puts through the
        guarded staging site.  The group's corpora stay retained by the
        pipeline until the drain commits them, so the recovery point can
        re-slice them over a rebuilt mesh.  The staged record carries the
        group along — the drain commits per input chunk."""
        nonlocal cap
        need = max(c.n_tokens for c in group)
        cap, _ = grow_chunk_cap(need, cap, metrics)
        doc_ids = np.zeros((d, cap), np.int32)
        term_ids = np.zeros((d, cap), np.int32)
        valid = np.zeros((d, cap), bool)
        for i, c in enumerate(group):
            doc_ids[i, : c.n_tokens] = c.doc_ids
            term_ids[i, : c.n_tokens] = c.term_ids
            valid[i, : c.n_tokens] = True
        dev = dflow.staged_put(
            lambda: (jax.device_put(doc_ids, esh),
                     jax.device_put(term_ids, esh),
                     jax.device_put(valid, esh)),
            metrics=metrics,
        )
        return (group, dev)

    def launch_group(staged):
        nonlocal step
        group, (d_doc, d_term, d_valid) = staged
        t0 = time.perf_counter()
        (c_doc, c_term, c_cnt, c_np, _c_valid), df = kernel(
            d_doc, d_term, d_valid
        )  # async dispatch — the pull waits in the drain
        rec = (group, step, c_doc, c_term, c_cnt, c_np, df, t0)
        step += 1
        return rec

    def drain_group(rec) -> None:
        group, step_i, c_doc, c_term, c_cnt, c_np, df, t0 = rec
        with obs.span("tfidf.super_chunk", step=step_i,
                      chunk=st.chunk_index):
            # per-device shard-ready times onto the bus BEFORE the batched
            # pull, so the trace's chunk timeline can attribute a slow
            # super-chunk to the straggling device (hardening (d))
            _publish_device_timings(c_np, step_i)
            # One batched device->host pull: a single round-trip per
            # super-chunk instead of a fence plus four separate transfers
            # (each paying tunnel RTT).  Guarded: a transient failure
            # re-issues the pull against the live buffers; persistent
            # faults walk the ladder and surface to the pipeline's
            # recovery point (mesh shrink + re-slice of retained groups).
            h_doc, h_term, h_cnt, n_pairs, h_df = rx.device_get(
                (c_doc, c_term, c_cnt, c_np, df),
                site="tfidf_shard_sync", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )
        st.df_total = st.df_total + h_df.astype(dtype)
        n_pairs = np.asarray(n_pairs).ravel()
        for i, c in enumerate(group):
            k = int(n_pairs[i])
            # .copy() so parts holds k-sized arrays, not views pinning the
            # whole (d, cap) transfer buffer until finalize
            st.parts.append(
                (h_doc[i, :k].copy(), h_term[i, :k].copy(),
                 h_cnt[i, :k].copy())
            )
            st.doc_length_parts.append(c.doc_lengths)
        st.n_docs += int(sum(c.n_docs for c in group))
        st.chunk_index += len(group)
        st.n_tokens += int(sum(c.n_tokens for c in group))
        metrics.record(
            event="super_chunk", step=step_i, devices=len(group),
            docs=st.n_docs, tokens=int(sum(c.n_tokens for c in group)),
            secs=time.perf_counter() - t0,
        )

    def checkpoint_due() -> bool:
        if not (cfg.checkpoint_every > 0 and cfg.checkpoint_dir):
            return False
        return st.chunk_index - last_ckpt >= cfg.checkpoint_every

    def save_ckpt() -> None:
        nonlocal last_ckpt
        st.ingest_secs = secs0 + (time.perf_counter() - run_started)
        save_ingest_checkpoint(cfg, metrics, st, extra_meta={"devices": d})
        last_ckpt = st.chunk_index

    def regrouped(remaining: Iterator) -> Iterator[list]:
        # re-slice: flatten whatever group widths the dying mesh left in
        # flight and regroup to the CURRENT mesh width (``grouped`` reads
        # ``d`` per group — a second shrink inside the replay re-sizes
        # again)
        return grouped((None, c) for group in remaining for c in group)

    def recover(exc, remaining, where):
        """Mesh-shrink recovery point: on device loss anywhere in the
        pipeline (H2D put, dispatch, drain), checkpoint the committed
        ingest state, rebuild the mesh/kernel over the survivors, and
        re-slice the in-flight staged groups (retained as host corpora by
        the pipeline) over the shrunk mesh.  Committed chunks are
        untouched — zero reprocessing, same guarantee as the resume path.
        A further loss inside the replay re-enters here (the stacked-loss
        re-entry the elastic ladder requires)."""
        nonlocal mesh, d, esh, kernel, last_ckpt
        # Salvage committed work FIRST: whatever happens next (shrink or
        # re-raise into the legacy ladder), the chunks already committed
        # must survive as a snapshot.  The old loop had this for free —
        # its periodic save ran before the next drain could fail; the
        # pipeline's drain-before-commit barrier can order a failing
        # drain ahead of a due checkpoint.
        saved = None
        if cfg.checkpoint_dir and st.parts:
            st.ingest_secs = secs0 + (time.perf_counter() - run_started)
            save_ingest_checkpoint(cfg, metrics, st,
                                   extra_meta={"devices": d})
            last_ckpt = st.chunk_index
            saved = ckpt.latest_checkpoint(cfg.checkpoint_dir)

        def reraise():
            # an exhausted ladder raised before the salvage above existed
            # must still hand the caller the freshest snapshot
            if (saved is not None
                    and isinstance(exc, rx.ResilienceExhausted)
                    and exc.last_checkpoint is None):
                raise rx.ResilienceExhausted(
                    exc.site, exc.attempts, exc.last_error, saved
                ) from exc
            raise exc

        lost = elastic.unwrap_device_loss(exc)
        if not elastic.enabled() or lost is None:
            reraise()
        idx = elastic.device_index(lost)
        if idx is not None:
            elastic.health().mark_lost(idx)
        plan = elastic.plan_shrink(list(mesh.devices.flat))
        if plan is None:
            reraise()
        site = {"stage": dflow.H2D_PUT_SITE,
                "wait": dflow.H2D_WAIT_SITE}.get(where, "tfidf_shard_sync")
        with elastic.publish_shrink(site, plan, lost, metrics):
            # keep the dying mesh's axis name: a caller-provided mesh may
            # not be named DATA_AXIS, and esh below is built from ``axis``
            mesh = rebuild_mesh(plan.devices, axis)
            d = plan.new_count
            esh = NamedSharding(mesh, P(axis, None))
            kernel = make_sharded_counts_kernel(mesh, vocab)
        return regrouped(remaining)

    with obs.span("tfidf.shard_stream", devices=d,
                  resume_chunk=st.chunk_index):
        dflow.chunked_ingest(
            grouped(chunk_source),
            stage=stage_group,
            launch=launch_group,
            drain=drain_group,
            commit=lambda: None,  # the drain's pull IS the commit: DF is
            # psum'd and pulled per super-chunk, nothing stays on device
            ingest=cfg.ingest(),
            checkpoint_due=checkpoint_due,
            save_checkpoint=save_ckpt,
            recover=recover,
            metrics=metrics,
        )

    return finalize_tfidf(st, cfg, metrics)
