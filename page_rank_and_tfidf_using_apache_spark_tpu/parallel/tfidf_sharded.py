"""Multi-chip TF-IDF: data-parallel chunk ingest with psum'd DF and a
replicated IDF broadcast.

Reference counterpart (SURVEY.md §2.2 R1–R3, BASELINE.json:11): Spark
splits the corpus into partitions, shuffles ((term, doc), 1) records for the
TF and DF passes, and torrent-broadcasts small tables.  Here each device
ingests its own fixed-shape token chunk (documents never span chunks, so
per-chunk run-length DF increments are exact), one ``psum`` over the mesh
combines the per-device DF vectors — the DF `reduceByKey` — and the
resulting IDF vector is *replicated* across chips, which is BASELINE.json:5's
"IDF broadcast across chips" realized as a sharding annotation instead of a
torrent protocol.

Shapes: a "super-chunk" is [D, cap] token arrays, one row per device;
compile happens once per (D, cap).
"""

from __future__ import annotations

import time
from typing import Iterable, Sequence

import jax
import numpy as np
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    IngestState,
    TfidfOutput,
    _prefetched,
    _tokenized_chunks,
    finalize_tfidf,
    grow_chunk_cap,
    resume_ingest,
    save_ingest_checkpoint,
)
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import collectives as coll
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    rebuild_mesh,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig, ensure_dtype_support
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


def _publish_device_timings(arr, step: int) -> None:
    """Per-device shard-ready timings for the trace chunk timeline
    (ROADMAP hardening (d)): fence each device's shard of the tiny
    ``n_pairs`` vector and record when it became ready, measured from the
    call.  Shards are waited in device order, so entry ``i`` is an upper
    bound for a device that finished while an earlier one still ran — the
    straggler (the max) is exact, which is what load-balance debugging
    needs.  Best-effort telemetry: any fault here is left for the guarded
    batched pull that follows.  Runs ONLY under an active traced run —
    untraced ingest keeps the single batched pull as its only sync (on a
    tunnel-attached TPU each per-shard fence is a real host round-trip,
    and with no run the event would be discarded anyway)."""
    if obs.current_run() is None:
        return
    try:
        t0 = time.perf_counter()
        secs = []
        for s in arr.addressable_shards:
            s.data.block_until_ready()  # graftlint: disable=unguarded-host-sync,host-sync-in-loop (per-shard fence for telemetry only; the guarded batched pull right after owns retry/deadline/degradation)
            secs.append(round(time.perf_counter() - t0, 6))
        obs.emit("device_timing", site="tfidf_super_chunk", step=step,
                 devices=len(secs), secs=secs)
    except Exception:  # noqa: BLE001 — never let telemetry kill ingest
        pass


def make_sharded_counts_kernel(mesh: Mesh, vocab: int):
    """Compile: [D, cap] tokens → per-device counts + globally-psum'd DF."""
    axis = mesh.axis_names[0]

    def kernel(doc_ids, term_ids, valid):
        counts = ops.count_pairs(doc_ids[0], term_ids[0], token_valid=valid[0])
        df_local = ops.document_frequency(counts, vocab)
        df = coll.psum(df_local, axis)  # the DF reduceByKey, on ICI
        # re-add the device axis so out_specs can shard along it
        return (counts.doc[None], counts.term[None], counts.count[None],
                counts.n_pairs[None], counts.valid[None]), df

    esh = P(axis, None)
    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(esh, esh, esh),
            out_specs=(
                (esh, esh, esh, P(axis), esh),
                P(),  # DF replicated — the IDF broadcast target
            ),
            check_vma=False,
        )
    )


def run_tfidf_sharded(
    doc_chunks: Iterable[Sequence[str]],
    cfg: TfidfConfig,
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> TfidfOutput:
    """Sharded counterpart of models.tfidf.run_tfidf_streaming: consumes the
    same chunk iterator, ingesting D chunks per device step.  Checkpointing
    shares the streaming path's format (``cfg.checkpoint_every`` counts input
    *chunks*, not super-chunks, so a config moved between the two paths
    checkpoints at the same cadence) and ``resume=True`` skips the
    already-ingested prefix of the iterator."""
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, DATA_AXIS)
    d = int(mesh.devices.size)
    axis = mesh.axis_names[0]
    vocab = cfg.vocab_size
    dtype = cfg.dtype

    cap = cfg.chunk_tokens
    kernel = None
    esh = NamedSharding(mesh, P(axis, None))

    st = (resume_ingest(cfg, metrics) if resume
          else IngestState(df_total=np.zeros(vocab, dtype)))
    last_ckpt = st.chunk_index
    secs0 = st.ingest_secs
    run_started = time.perf_counter()

    # Tokenize on a background thread, up to cfg.prefetch chunks ahead
    # (SURVEY.md §5.7 — same double-buffering as the single-chip streaming
    # path; cfg.prefetch=0 keeps everything on the calling thread).  The
    # consumer pulls d chunks per super-chunk incrementally, so the buffer
    # bound stays exactly what the user asked for.
    source = _tokenized_chunks(doc_chunks, cfg, st.chunk_index, st.n_docs)
    if cfg.prefetch > 0:
        source = _prefetched(source, int(cfg.prefetch))
    chunk_iter = iter(source)
    step = 0
    while True:
        group: list[tio.TokenizedCorpus] = []
        for _ in range(d):
            item = next(chunk_iter, None)
            if item is None:
                break
            _, corpus = item
            group.append(corpus)
        if not group:
            break
        need = max(c.n_tokens for c in group)
        cap, changed = grow_chunk_cap(need, cap, metrics)
        if changed:
            kernel = None
        if kernel is None:
            kernel = make_sharded_counts_kernel(mesh, vocab)

        # st is NOT touched until the pull commits below: the elastic rung
        # may checkpoint st mid-group, and a snapshot must only ever hold
        # fully-committed chunks (n_docs for an uncommitted group would
        # poison the resume-side chunking validation).
        doc_ids = np.zeros((d, cap), np.int32)
        term_ids = np.zeros((d, cap), np.int32)
        valid = np.zeros((d, cap), bool)
        for i, c in enumerate(group):
            doc_ids[i, : c.n_tokens] = c.doc_ids
            term_ids[i, : c.n_tokens] = c.term_ids
            valid[i, : c.n_tokens] = True

        def elastic_reslice(exc, doc_ids=doc_ids, term_ids=term_ids,
                            valid=valid):
            """Mesh-shrink rung: on device loss, checkpoint the committed
            ingest state, rebuild the mesh/kernel over the survivors, and
            re-slice the in-flight super-chunk (never-committed work) into
            new-width dispatches.  Committed chunks are untouched — zero
            reprocessing, same guarantee as the resume path."""
            nonlocal mesh, d, esh, kernel, last_ckpt
            if not elastic.enabled() or not elastic.is_device_loss(exc):
                raise exc
            idx = elastic.device_index(exc)
            if idx is not None:
                elastic.health().mark_lost(idx)
            if cfg.checkpoint_dir and st.parts:
                st.ingest_secs = secs0 + (time.perf_counter() - run_started)
                save_ingest_checkpoint(cfg, metrics, st,
                                       extra_meta={"devices": d})
                last_ckpt = st.chunk_index
            plan = elastic.plan_shrink(list(mesh.devices.flat))
            if plan is None:
                raise exc
            with elastic.publish_shrink("tfidf_shard_sync", plan, exc,
                                        metrics):
                # keep the dying mesh's axis name: a caller-provided mesh
                # may not be named DATA_AXIS, and esh below is built from
                # the same ``axis``
                mesh = rebuild_mesh(plan.devices, axis)
                d = plan.new_count
                esh = NamedSharding(mesh, P(axis, None))
                kernel = make_sharded_counts_kernel(mesh, vocab)
            rows = doc_ids.shape[0]
            outs: list[tuple] = []
            df_sum = None
            with obs.span("tfidf.reslice", rows=rows, width=d):
                lo = 0
                while lo < rows:
                    batch = slice(lo, lo + d)
                    b_doc = np.zeros((d, cap), np.int32)
                    b_term = np.zeros((d, cap), np.int32)
                    b_valid = np.zeros((d, cap), bool)
                    n_rows = doc_ids[batch].shape[0]
                    b_doc[:n_rows] = doc_ids[batch]
                    b_term[:n_rows] = term_ids[batch]
                    b_valid[:n_rows] = valid[batch]
                    try:
                        (r_doc, r_term, r_cnt, r_np, _rv), r_df = kernel(
                            jax.device_put(b_doc, esh),
                            jax.device_put(b_term, esh),
                            jax.device_put(b_valid, esh),
                        )
                        # one batched pull per re-sliced dispatch: the
                        # shrunk mesh processes the in-flight rows
                        # sequentially, so each sub-dispatch syncs before
                        # the next launches
                        h = rx.device_get(  # graftlint: disable=host-sync-in-loop (one batched pull per re-sliced dispatch on the rare shrink path)
                            (r_doc, r_term, r_cnt, r_np, r_df),
                            site="tfidf_shard_sync", metrics=metrics,
                            checkpoint_dir=cfg.checkpoint_dir,
                        )
                    except Exception as exc2:  # noqa: BLE001 — re-caught below
                        # A SECOND device dying inside the shrink-rerun
                        # (ISSUE 8 elastic gap): re-enter the ladder —
                        # mark the new loss, plan the next shrink from the
                        # CURRENT (already-shrunk) mesh, rebuild the
                        # kernel, and re-dispatch the same rows at the new
                        # width.  Committed rows (< lo) stay committed.
                        lost = elastic.unwrap_device_loss(exc2)
                        if lost is None or not elastic.enabled():
                            raise
                        idx2 = elastic.device_index(lost)
                        if idx2 is not None:
                            elastic.health().mark_lost(idx2)
                        plan2 = elastic.plan_shrink(list(mesh.devices.flat))
                        if plan2 is None:
                            raise
                        with elastic.publish_shrink(
                            "tfidf_shard_sync", plan2, lost, metrics
                        ):
                            mesh = rebuild_mesh(plan2.devices, axis)
                            d = plan2.new_count
                            esh = NamedSharding(mesh, P(axis, None))
                            kernel = make_sharded_counts_kernel(mesh, vocab)
                        continue  # same lo: nothing from this batch committed
                    outs.append(h[:4])
                    df_sum = h[4] if df_sum is None else df_sum + h[4]
                    lo += n_rows
            return (
                np.concatenate([o[0] for o in outs]),
                np.concatenate([o[1] for o in outs]),
                np.concatenate([o[2] for o in outs]),
                np.concatenate([np.atleast_1d(o[3]).ravel() for o in outs]),
                df_sum,
            )

        with Timer() as t, obs.span("tfidf.super_chunk", step=step,
                                    chunk=st.chunk_index):
            (c_doc, c_term, c_cnt, c_np, _c_valid), df = kernel(
                jax.device_put(doc_ids, esh),
                jax.device_put(term_ids, esh),
                jax.device_put(valid, esh),
            )
            # per-device shard-ready times onto the bus BEFORE the batched
            # pull, so the trace's chunk timeline can attribute a slow
            # super-chunk to the straggling device (hardening (d))
            _publish_device_timings(c_np, step)
            # One batched device->host pull: a single round-trip per
            # super-chunk instead of a block_until_ready fence plus four
            # separate np.asarray transfers (each paying tunnel RTT).
            # Guarded: a transient failure re-issues the pull against the
            # live buffers; device loss shrinks the mesh (elastic rung);
            # exhaustion carries the chunk checkpoint.
            h_doc, h_term, h_cnt, n_pairs, h_df = rx.device_get(
                (c_doc, c_term, c_cnt, c_np, df),
                site="tfidf_shard_sync", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
                fallbacks=[(None, elastic_reslice)],
            )
        st.df_total = st.df_total + h_df.astype(dtype)
        n_pairs = n_pairs.ravel()
        for i, c in enumerate(group):
            k = int(n_pairs[i])
            # .copy() so parts holds k-sized arrays, not views pinning the
            # whole (d, cap) transfer buffer until finalize
            st.parts.append(
                (h_doc[i, :k].copy(), h_term[i, :k].copy(), h_cnt[i, :k].copy())
            )
            st.doc_length_parts.append(c.doc_lengths)
        st.n_docs += int(sum(c.n_docs for c in group))
        st.chunk_index += len(group)
        st.n_tokens += int(sum(c.n_tokens for c in group))
        metrics.record(
            event="super_chunk", step=step, devices=len(group), docs=st.n_docs,
            tokens=int(sum(c.n_tokens for c in group)), secs=t.elapsed,
        )
        step += 1
        if (
            cfg.checkpoint_every > 0 and cfg.checkpoint_dir
            and st.chunk_index - last_ckpt >= cfg.checkpoint_every
        ):
            st.ingest_secs = secs0 + (time.perf_counter() - run_started)
            save_ingest_checkpoint(cfg, metrics, st,
                                   extra_meta={"devices": d})
            last_ckpt = st.chunk_index

    return finalize_tfidf(st, cfg, metrics)
