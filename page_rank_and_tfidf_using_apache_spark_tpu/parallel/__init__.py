from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    NODES_AXIS,
    init_distributed,
    make_mesh,
    replicated,
    sharded_along,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
    ShardedGraph,
    auto_select_strategy,
    partition_graph,
    run_pagerank_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.tfidf_sharded import (
    run_tfidf_sharded,
)
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.workloads_sharded import (
    run_components_sharded,
    run_hits_sharded,
    run_ppr_sharded,
)

__all__ = [
    "DATA_AXIS",
    "NODES_AXIS",
    "init_distributed",
    "make_mesh",
    "replicated",
    "sharded_along",
    "ShardedGraph",
    "auto_select_strategy",
    "partition_graph",
    "run_pagerank_sharded",
    "run_tfidf_sharded",
    "run_components_sharded",
    "run_hits_sharded",
    "run_ppr_sharded",
]
