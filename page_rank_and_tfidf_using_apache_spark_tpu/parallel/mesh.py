"""Device mesh construction + multi-host initialization.

Reference counterpart (SURVEY.md §2.2 R7/R8, §5.8): Spark's executor pool
and netty transport.  Here the communication substrate is the TPU fabric:
one ``jax.sharding.Mesh`` whose collectives ride **ICI** within a pod slice
and **DCN** across hosts — the same collective code serves both, which is
the whole point of replacing the reference's shuffle with XLA collectives.

Only one physical chip exists in this build environment, so multi-chip
paths are validated on XLA's simulated host devices
(``--xla_force_host_platform_device_count``, SURVEY.md §4); the mesh code is
shape-generic and does not care which backend provides the devices.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


NODES_AXIS = "nodes"  # rank-vector / node-block axis (model-parallel SpMV)
DATA_AXIS = "data"  # document/chunk axis (data-parallel TF-IDF ingest)


def make_mesh(
    n_devices: int | None = None,
    axis: str = NODES_AXIS,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default).

    Both algorithms in scope shard along a single axis (SURVEY.md §2.3: DP
    over edges/docs plus 1-D TP of the rank vector), so a 1-D mesh is the
    native shape; a 2-D (dcn, ici) refinement would slot in here for
    multi-host runs without touching callers.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} available"
            )
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (axis,))


def largest_pow2(n: int) -> int:
    """Largest power of two <= n (0 for n < 1)."""
    if n < 1:
        return 0
    return 1 << (n.bit_length() - 1)


def shrink_devices(devices: Sequence[jax.Device]) -> list[jax.Device]:
    """Truncate a surviving-device list to the largest power-of-two count.

    The elastic rung (resilience/elastic.py) rebuilds meshes only at
    power-of-two sizes: both sharded runners pad their partitions to the
    device count, so halving the mesh at worst doubles per-device state —
    the same bound the partition planners already budget for — while an
    arbitrary shrink (say 8 -> 7) would produce a one-off shape that
    recompiles without that guarantee.  Returns ``[]`` when nothing
    survives (the caller falls through to the CPU rung)."""
    return list(devices)[: largest_pow2(len(devices))]


def rebuild_mesh(devices: Sequence[jax.Device], axis: str) -> Mesh:
    """1-D mesh over exactly ``devices`` — the mesh-rebuild entry point the
    elastic rung uses after :func:`shrink_devices` picked the survivors.
    Identical to ``make_mesh(devices=...)``; named so call sites read as
    what they are."""
    return make_mesh(axis=axis, devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def sharded_along(mesh: Mesh, axis: str) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis))


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host init hook (SURVEY.md §5.8): call once per host before any
    device op; afterwards ``jax.devices()`` spans the whole DCN-connected
    slice and ``make_mesh`` + the sharded runners work unchanged.

    Untestable with a single host (SURVEY.md §7 'kept thin'): delegates
    straight to ``jax.distributed.initialize``, which reads cluster env vars
    when args are None.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
