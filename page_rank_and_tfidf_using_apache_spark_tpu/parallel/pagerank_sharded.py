"""Multi-chip PageRank: sharded CSR SpMV with XLA collectives.

Reference counterpart (SURVEY.md §2.2 R1/R2, BASELINE.json:9): Spark's
hash-partitioned RDDs and the shuffle that re-co-partitions
``links.join(ranks)`` every iteration.  Here the graph is partitioned
**once** on host, laid out per device, and every iteration's cross-chip
combine is a single XLA collective over ICI — no repartitioning ever
happens because the partition is static and the collective does the moving.

Two sharding strategies (SURVEY.md §7 "power-law load imbalance" is why
both exist):

- ``edges`` (default): each device owns an equal *contiguous slice of the
  dst-sorted edge array* — perfectly balanced FLOPs even on power-law
  graphs (a celebrity node's in-edges simply span devices).  The rank
  vector is replicated; each device segment-sums its slice into a full-size
  partial and one ``psum`` combines partials (the `reduceByKey`).
  Dangling mass needs no collective (replicated state).
- ``nodes``: each device owns a *block of nodes* (rank shard + that block's
  in-edges) — memory scales 1/D, the layout for graphs whose node state
  outgrows one chip's HBM (soc-LiveJournal1 config, BASELINE.json:9).
  Per iteration: ``all_gather`` the degree-weighted rank blocks, local
  segment_sum into the block, ``psum`` only for the dangling-mass scalar.
- ``nodes_balanced``: same memory layout and iteration as ``nodes``, but the
  node-block boundaries are chosen at equal *in-edge* splits instead of
  equal node counts, so a power-law degree distribution (one celebrity node
  next to millions of leaves) no longer concentrates most of the SpMV work
  on one chip.  Node ids are relabeled into a padded per-device space on
  host (``node_map``); the device program is identical to ``nodes``.  The
  padded block is uniform (= the max device's node count), so per-device
  node counts are capped at 2x the equal-node block — memory stays within
  2x of ``nodes`` instead of degrading toward n*d on hub-heavy graphs.
- ``src`` / ``src_ring``: the *push* layout (SURVEY.md §2.3 "all-to-all"
  row, §5.8 edge-cut exchange).  Device i owns source block i — rank shard
  plus its nodes' out-edges — so the per-edge gather reads only the local
  1/D-sized rank block (never a gathered [n_pad] vector), each device
  segment-sums a full per-destination partial, and one **reduce-scatter**
  combines and re-shards it in a single collective: half the bytes of the
  ``edges`` psum, and immune to hub *in*-degree imbalance (edges follow
  their source; out-degree is the bounded axis of web graphs).
  ``src_ring`` runs the identical exchange as an explicit ``ppermute``
  ring (collectives.ring_reduce_scatter) — the hand-scheduled hop-by-hop
  form whose equality with psum_scatter tests pin.
- ``hybrid``: the degree-aware power-law layout (*Sparse Allreduce*'s
  dense-head/sparse-tail split, PAPERS.md).  Replicated rank vector like
  ``edges``; the high-in-degree head's edges live as fixed-width dense
  rows (ops.pagerank.HybridLayout) split evenly across devices and
  reduced on the MXU, the long tail as equal contiguous dst-sorted edge
  slices; each device's full-size partial combines in the same single
  ``psum``.  Because BOTH sides split at edge/row granularity — a hub's
  dense rows simply span devices — the power-law in-degree imbalance that
  pads ``nodes``/``nodes_balanced`` to 0.6 cannot occur: the plan-level
  ``pad_frac`` stays at the ceil-remainder level of ``edges`` plus the
  head rows' sentinel slots.
- ``owned``: the break-the-replicated-state-wall layout (ISSUE 15;
  *Sparse Allreduce*'s hub-peeled sparse exchange over DrJAX-style native
  collectives — see ``ops/boundary.py`` for the full anatomy).  Each
  shard owns ONLY its tail block's rank slice; a small combined-degree
  hub head is the one replicated mini-state (its contributions combine
  in ONE [H_pad+2] ``psum`` that also carries the dangling mass and the
  one-step-lagged global delta — so per step the ONLY collectives are
  the log₂(d) ``ppermute`` rounds of the boundary butterfly plus that
  single psum); every other cross-shard read moves through fixed-width
  padded boundary buffers holding just the cut-crossing entries.  State
  per chip is O(n/d + H), comm per step is O(boundary + H) — both
  sublinear in n on power-law graphs, which is what lets 10-100x
  web-Google node counts run at all.
- ``auto``: picks by memory footprint and degree shape — ``hybrid`` when
  the replicated node state fits per-chip HBM and the graph has a
  dense-worthy power-law head, ``edges`` when it fits but has no head,
  ``owned`` beyond (replicated-state-doesn't-fit is the trigger; see
  :func:`auto_select_strategy`).

Both run the whole iteration loop inside one ``jit`` + ``shard_map``
program: collectives are compiled into the loop body, so there are zero
host round-trips between iterations, same as the single-chip path.

``spark_exact`` mode is single-chip-only (it exists for parity testing, not
scale) — requesting it sharded raises.
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import shard_map

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dataflow
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    PartitionedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    OwnedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.models import driver
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import PageRankResult
from page_rank_and_tfidf_using_apache_spark_tpu.ops import boundary as ob
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import collectives as coll
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
    NODES_AXIS,
    make_mesh,
    rebuild_mesh,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TUNABLE_DEFAULTS,
    DanglingMode,
    PageRankConfig,
    RankInit,
    ensure_dtype_support,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


DEFAULT_HBM_BYTES = 8 << 30  # conservative per-chip working budget (v5e: 16G)


def replicated_state_bytes(
    n_nodes: int, n_edges: int, n_devices: int, dtype: str = "float32"
) -> int:
    """The per-chip footprint of a REPLICATED-rank strategy: ~6 node
    vectors live at once (ranks, new ranks, contribs, inv_outdeg,
    dangling, e) plus this chip's edge slice (src/dst int32 + the
    coefficient mask).  One model shared by :func:`auto_select_strategy`
    and the replicated-wall assertions in bench.py/__graft_entry__.py —
    the selector and the acceptance harnesses must not drift apart."""
    item = np.dtype(dtype).itemsize
    node_state = 6 * n_nodes * item
    edge_state = int(n_edges / max(n_devices, 1) * (8 + item))
    return int(node_state + edge_state)


def auto_select_strategy(
    graph: Graph,
    n_devices: int,
    *,
    dtype: str = "float32",
    hbm_bytes: int | None = None,
    head_coverage: float = TUNABLE_DEFAULTS["head_coverage"],
    head_row_width: int = TUNABLE_DEFAULTS["head_row_width"],
) -> str:
    """Pick a shard strategy by per-chip memory footprint.

    ``edges`` replicates every node-sized vector on every chip (no memory
    scaling — the round-1 gap for soc-LiveJournal1-sized graphs), so once
    the replicated node state plus this chip's edge slice stops fitting in
    half the HBM working budget, switch to ``nodes_balanced``: 1/D node
    state with edge-balanced blocks.  Overridable via the
    ``PR_TFIDF_HBM_BYTES`` env var (tests use it to force the switch).
    """
    import os

    if hbm_bytes is None:
        hbm_bytes = int(os.environ.get("PR_TFIDF_HBM_BYTES", DEFAULT_HBM_BYTES))
    replicated = replicated_state_bytes(
        graph.n_nodes, graph.n_edges, n_devices, dtype
    )
    # Every exit publishes ONE strategy_decision event carrying the
    # measured inputs, so trace_report can show WHY a run picked its
    # strategy (ISSUE 9 satellite) — today the choice was invisible in
    # traces.  No-op outside a traced run.
    inputs = dict(
        devices=n_devices,
        nodes=graph.n_nodes, edges=graph.n_edges,
        replicated_state_bytes=replicated,
        hbm_bytes=int(hbm_bytes),
    )
    if replicated > hbm_bytes / 2:
        # Replicated state does not fit: owned slices + sparse boundary
        # exchange (ISSUE 15) — O(n/d + H) state per chip where the older
        # nodes_balanced layout still all_gathers O(n) bytes per step.
        # The owned butterfly needs a power-of-two mesh (the same shapes
        # the elastic shrink chain rebuilds at); a non-pow2 count keeps
        # the legacy memory-scaling layout.
        pow2 = n_devices >= 1 and n_devices & (n_devices - 1) == 0
        obs.emit("strategy_decision",
                 chosen="owned" if pow2 else "nodes_balanced",
                 reason="replicated node state exceeds half the per-chip "
                        "HBM budget", **inputs)
        return "owned" if pow2 else "nodes_balanced"
    # Replicated state fits — prefer the degree-aware hybrid layout when
    # the graph has a dense-worthy power-law head covering a meaningful
    # fraction of the edges (the dense MXU rows then carry the hot
    # in-degree mass scatter-free); plain ``edges`` otherwise.  A
    # weighted graph never picks hybrid: its sharded form has no
    # weighted dense rows (partition_graph would refuse).
    indeg = np.diff(graph.csr_indptr())
    # evaluate the head at the SAME knobs the partition will materialize
    # with — plan_hybrid_head's planner/builder agreement contract
    head_ids, _w = ops.plan_hybrid_head(
        indeg, graph.n_edges, coverage=head_coverage,
        row_width=head_row_width,
    )
    head_edges = int(indeg[head_ids].sum()) if head_ids.size else 0
    inputs.update(head_nodes=int(head_ids.size), head_edges=head_edges,
                  head_edge_frac=round(head_edges / max(graph.n_edges, 1), 4))
    if (head_ids.size and head_edges >= graph.n_edges // 4
            and graph.weight is None):
        obs.emit("strategy_decision", chosen="hybrid",
                 reason="replicated state fits and the power-law head "
                        "covers >=25% of edges", **inputs)
        return "hybrid"
    obs.emit("strategy_decision", chosen="edges",
             reason="replicated state fits; no dense-worthy degree head",
             **inputs)
    return "edges"


class PartitionPlan(NamedTuple):
    """Pure *planning* output of a shard strategy: split boundaries, padded
    sizes and the padding-waste fraction, computed without materializing a
    single per-device array (and without any device dispatch).

    This is the introspection surface the graftlint tier-3 pad_frac
    analyzer gates on (``analysis/cost.py``): ``partition_graph`` builds its
    arrays FROM this plan, so the static number the linter budgets is — by
    construction, not by convention — the same ``pad_frac`` a real
    multichip run logs in its ``partition`` event (cross-checked against
    MULTICHIP_r05.json by tests/test_cost_lint.py)."""

    strategy: str
    n: int  # real node count
    n_pad: int  # D * block
    block: int  # nodes per device block
    e_dev: int  # edge slots per device (padded width; tail-only for hybrid)
    pad_frac: float  # fraction of padded edge slots (load-imbalance gauge)
    bounds_nodes: np.ndarray | None = None  # [D+1] node-block boundaries
    ebounds: np.ndarray | None = None  # [D+1] edge-range boundaries (nodes*)
    per: np.ndarray | None = None  # [D] real edges per device ('src*')
    # 'hybrid' only: (head node count, dense row width, total dense rows,
    # dense rows per device) — the head side of the slot accounting
    head: tuple[int, int, int, int] | None = None
    # 'owned' only: the full boundary-exchange plan (ops.boundary.OwnedPlan
    # — head set, tail bounds, boundary sets, pad + comm accounting);
    # partition_graph materializes exactly it
    owned: ob.OwnedPlan | None = None
    # Array entries each device sends per iteration under this plan (the
    # static per-step comm footprint — ICI bytes = entries * itemsize);
    # published with the partition event and gauged by _ShardedExec so
    # trace_diff can regress it across rounds (ISSUE 15 satellite).
    comm_entries_per_step: int | None = None



def _comm_entries(strategy: str, d: int, n_pad: int, block: int,
                  owned_plan: "ob.OwnedPlan | None" = None) -> int:
    """Static per-step comm footprint of a partition plan, in array
    entries sent per device per iteration (ring-scheduled collectives:
    allreduce ~2 passes, gather/scatter ~1).  The replicated strategies
    move O(n_pad) per step; ``owned`` moves only the padded boundary
    buffers plus the head psum — the sublinearity the MULTICHIP scale
    sweep measures."""
    if d <= 1:
        return 0
    if strategy == "owned":
        assert owned_plan is not None
        return owned_plan.comm_entries_per_step()
    if strategy in ("edges", "hybrid"):  # dense [n_pad] psum
        return 2 * n_pad * (d - 1) // d
    # nodes*/src*: all_gather / reduce-scatter of the block axis, plus
    # two scalar psums (dangling mass + delta)
    return (d - 1) * block + 4


def _publish_plan(plan: PartitionPlan, n_devices: int) -> PartitionPlan:
    """Log the chosen partition plan (strategy + the numbers that drove
    it) as ONE obs event, so a trace explains the layout a run executed
    with (ISSUE 9 satellite: trace_report's strategy section).  No-op
    outside a traced run — the tier-3 lint calls plan_partition freely."""
    plan = plan._replace(
        comm_entries_per_step=_comm_entries(
            plan.strategy, n_devices, plan.n_pad, plan.block, plan.owned
        )
    )
    ow = plan.owned
    obs.emit(
        "partition_plan", strategy=plan.strategy, devices=n_devices,
        n=plan.n, n_pad=plan.n_pad, block=plan.block, e_dev=plan.e_dev,
        pad_frac=round(float(plan.pad_frac), 6),
        head=(list(plan.head) if plan.head is not None else None),
        comm_entries_per_step=plan.comm_entries_per_step,
        **(
            dict(
                owned_head=ow.h, owned_h_pad=ow.h_pad, owned_b_pad=ow.b_pad,
                boundary_total=int(ow.boundary_counts.sum()),
                boundary_pad_frac=round(float(ow.boundary_pad_frac), 6),
            )
            if ow is not None else {}
        ),
    )
    return plan


def plan_partition(
    graph: Graph,
    n_devices: int,
    *,
    strategy: str = "edges",
    head_coverage: float = TUNABLE_DEFAULTS["head_coverage"],
    head_row_width: int = TUNABLE_DEFAULTS["head_row_width"],
    owned_max_head: int = TUNABLE_DEFAULTS["owned_max_head"],
) -> PartitionPlan:
    """Plan a partition without building it: boundaries, padded widths and
    ``pad_frac`` only — O(E) host work, no per-device arrays, no device
    traffic.  ``partition_graph`` materializes exactly this plan."""
    if strategy not in ("edges", "nodes", "nodes_balanced", "src", "src_ring",
                        "hybrid", "owned"):
        raise ValueError(f"unknown shard strategy {strategy!r}")
    d = n_devices
    n = graph.n_nodes
    e = graph.n_edges

    if strategy == "owned":
        # The whole boundary-exchange plan lives in ops.boundary (head
        # set, min-max tail bounds, per-owner boundary sets, pad + comm
        # accounting); this wrapper only adapts it to the PartitionPlan
        # introspection surface the tier-3 pad gauge budgets.
        op = ob.plan_owned(graph, d, coverage=head_coverage,
                           max_head=owned_max_head)
        return _publish_plan(
            PartitionPlan(strategy, n, op.n_pad, op.block, op.e_dev,
                          op.pad_frac, owned=op),
            d,
        )

    if strategy == "hybrid":
        # Replicated-state layout: head rows and tail edges both split at
        # row/edge granularity, so the only padding is the dense rows'
        # sentinel slots plus two ceil remainders.  pad_frac counts ALL
        # dispatched slots (head row slots + tail edge slots) against the
        # real edge count — comparable with the other strategies' gauge.
        block = max(1, math.ceil(n / d))
        indeg = np.diff(graph.csr_indptr())
        head_ids, w = ops.plan_hybrid_head(
            indeg, e, coverage=head_coverage, row_width=head_row_width
        )
        head_deg = indeg[head_ids]
        rows = int((-(-head_deg // w)).sum()) if head_ids.size else 0
        rows_dev = math.ceil(rows / d) if rows else 0
        e_tail = e - int(head_deg.sum())
        e_dev = max(1, math.ceil(e_tail / d))
        slots = d * (e_dev + rows_dev * w)
        pad_frac = (slots - e) / max(slots, 1)
        return _publish_plan(
            PartitionPlan(strategy, n, block * d, block, e_dev, pad_frac,
                          head=(int(head_ids.size), int(w), rows, rows_dev)),
            d,
        )

    if strategy in ("src", "src_ring"):
        block = max(1, math.ceil(n / d))
        n_pad = block * d
        per = np.bincount(graph.src // block, minlength=d)
        e_dev = max(1, int(per.max()))
        pad_frac = (d * e_dev - e) / max(d * e_dev, 1)
        return _publish_plan(
            PartitionPlan(strategy, n, n_pad, block, e_dev, pad_frac,
                          per=per),
            d,
        )

    if strategy == "edges":
        block = max(1, math.ceil(n / d))
        e_dev = max(1, math.ceil(e / d))
        cap = e_dev * d
        pad_frac = (cap - e) / max(cap, 1)
        return _publish_plan(
            PartitionPlan(strategy, n, block * d, block, e_dev, pad_frac), d
        )

    if strategy == "nodes":
        block = max(1, math.ceil(n / d))
        bounds_nodes = np.minimum(np.arange(0, d + 1) * block, n)
    else:  # nodes_balanced
        # OPTIMAL min-max contiguous split (binary search over the padded
        # width + greedy max-fill feasibility), with per-device node count
        # capped at 2x the equal-node block: the uniform padded block is
        # the max device's node count, so an uncapped edge-balanced split
        # of a hub-heavy graph would push n_pad toward n*d and forfeit the
        # 1/D memory scaling this layout exists for.  The previous greedy
        # target-then-clamp scan planned up to 3x more padding than the
        # optimum on hub-heavy graphs (MULTICHIP_r05 measured 0.61 at 8
        # devices where the optimum is 0.47, and 0.45 at 4 where it is
        # 0.12); the node-granularity floor — a single hub's in-edge run
        # cannot split across devices in this layout — is what remains
        # (the 'hybrid' strategy exists to go below it).
        cap = 2 * max(1, math.ceil(n / d))
        indptr = graph.csr_indptr()

        def fill(width: int) -> np.ndarray | None:
            """Greedy max-fill at the given padded width; None = the n
            nodes do not fit on d devices at this width."""
            bounds = np.zeros(d + 1, np.int64)
            b = 0
            for i in range(d):
                hi = int(np.searchsorted(
                    indptr, indptr[b] + width, side="right")) - 1
                hi = min(max(hi, b), b + cap, n)
                bounds[i + 1] = hi
                b = hi
            return bounds if b >= n else None

        lo_w = max(1, math.ceil(e / d))
        hi_w = max(e, 1)
        bounds_nodes = fill(hi_w)
        assert bounds_nodes is not None  # d * cap >= 2n always covers n
        while lo_w < hi_w:
            mid = (lo_w + hi_w) // 2
            bm = fill(mid)
            if bm is None:
                lo_w = mid + 1
            else:
                hi_w, bounds_nodes = mid, bm
        block = max(1, int(np.diff(bounds_nodes).max()))
    ebounds = np.searchsorted(graph.dst, bounds_nodes)
    e_dev = max(1, int(np.diff(ebounds).max()))
    pad_frac = (d * e_dev - e) / max(d * e_dev, 1)
    return _publish_plan(
        PartitionPlan(strategy, n, block * d, block, e_dev, pad_frac,
                      bounds_nodes=bounds_nodes, ebounds=ebounds),
        d,
    )


class ShardedGraph(NamedTuple):
    """Host-side partitioned graph layout, ready for device_put.

    ``src`` is always global node ids; ``dst`` is block-local under the
    ``nodes`` strategy and global under ``edges``.  ``valid`` masks the
    per-device padding (power-law blocks pad unevenly under ``nodes``).
    """

    strategy: str
    n: int  # real node count
    n_pad: int  # D * block
    block: int  # nodes per device block
    src: np.ndarray  # int32 [D, E_dev]
    dst: np.ndarray  # int32 [D, E_dev]
    valid: np.ndarray  # f [D, E_dev]
    inv_outdeg: np.ndarray  # f [n_pad]
    dangling: np.ndarray  # f [n_pad] (padding rows are NOT dangling: 0)
    pad_frac: float  # fraction of padded edge slots (load-imbalance gauge)
    node_map: np.ndarray  # int64 [n]: global node id → padded slot
    # (identity-into-prefix for 'edges'/'nodes'; a relabeling under
    # 'nodes_balanced' where device blocks have unequal node counts)
    local_indptr: np.ndarray  # int32 [D, S+1]: per-device CSR row
    # pointers into that device's (sorted) edge slice, S = n_pad under
    # 'edges' / block under node strategies — the monotone-diff pointers
    # for spmv_impl='cumsum' (host memory cost D*S ints; sharded on device)
    # 'hybrid' only: this device's slice of the dense head rows.  Sentinel
    # source id n_pad reads the zero slot of the step's extended weight
    # vector; all-sentinel padding rows scatter 0.0 into node 0.
    head_src: np.ndarray | None = None  # int32 [D, R_dev, W]
    head_node: np.ndarray | None = None  # int32 [D, R_dev] global dst ids
    # 'owned' only: the materialized boundary-exchange layout (every
    # per-device array + the owned/replicated state vectors); the fields
    # above hold placeholder shapes for that strategy
    owned: ob.OwnedShard | None = None


def partition_graph(
    graph: Graph,
    n_devices: int,
    *,
    strategy: str = "edges",
    dtype: str = "float32",
    need_local_indptr: bool = True,
    head_coverage: float = TUNABLE_DEFAULTS["head_coverage"],
    head_row_width: int = TUNABLE_DEFAULTS["head_row_width"],
    owned_max_head: int = TUNABLE_DEFAULTS["owned_max_head"],
) -> ShardedGraph:
    """Partition once on host (the reference partitions on every shuffle).

    ``need_local_indptr=False`` skips the per-device CSR pointer build —
    only spmv_impl='cumsum' reads it, and under 'edges' it costs D
    node-sized int32 arrays (a (D, 1) placeholder is stored instead so the
    runner signature stays fixed).

    All split boundaries, padded widths and ``pad_frac`` come from
    :func:`plan_partition` — the static plan the tier-3 cost linter
    budgets is the one this function materializes.

    A weighted graph rides for free in every edge-mask strategy: the
    ``valid`` mask slots carry the edge WEIGHT instead of 1.0 (padding
    stays 0), so the per-edge product the step already computes becomes
    the weighted SpMV; ``inv_outdeg`` normalizes by out-strength.  The
    ``owned`` layout threads weights through its own coefficient arrays.
    Only ``hybrid`` refuses weights sharded (its dense head rows are
    weightless by construction — use another strategy or single-chip
    hybrid)."""
    plan = plan_partition(graph, n_devices, strategy=strategy,
                          head_coverage=head_coverage,
                          head_row_width=head_row_width,
                          owned_max_head=owned_max_head)
    d = n_devices
    n = graph.n_nodes
    e = graph.n_edges
    block, n_pad, e_dev, pad_frac = (
        plan.block, plan.n_pad, plan.e_dev, plan.pad_frac
    )

    if strategy == "owned":
        shard = ob.build_owned_shard(graph, plan.owned, dtype)
        ph = np.zeros((d, 1), np.int32)  # legacy-field placeholders
        return ShardedGraph(
            strategy, n, plan.n_pad, plan.block,
            src=ph, dst=ph, valid=np.zeros((d, 1), dtype),
            inv_outdeg=shard.inv_tail, dangling=shard.dang_tail,
            pad_frac=pad_frac, node_map=np.arange(n, dtype=np.int64),
            local_indptr=ph, owned=shard,
        )

    weighted = graph.weight is not None
    if weighted and strategy == "hybrid":
        raise NotImplementedError(
            "sharded strategy 'hybrid' has no weighted-edge form (the "
            "dense head rows carry no weight matrix); use 'owned', "
            "'edges' or a node strategy for weighted graphs"
        )
    # the per-edge coefficient the valid mask carries: weight or 1.0
    ew = graph.weight if weighted else None

    inv_g = graph.inv_out_strength(dtype)
    dang_g = (graph.out_degree == 0).astype(dtype)

    if strategy == "hybrid":
        # Materialize exactly the planned head/tail split: the global
        # hybrid layout (same plan_hybrid_head policy as the single-chip
        # impl), its dense rows dealt to devices in equal contiguous row
        # blocks, the tail as equal contiguous dst-sorted edge slices.
        hl = ops.build_hybrid_layout(
            graph, coverage=head_coverage, row_width=head_row_width
        )
        head_k, w, rows, rows_dev = plan.head
        assert hl.head_src.shape == (rows, w)  # plan IS the layout
        # head rows: remap the single-chip sentinel n -> n_pad (the zero
        # slot of the sharded step's extended weight vector)
        hsrc_g = hl.head_src.astype(np.int32).copy()
        hsrc_g[hsrc_g == n] = n_pad
        hnode_g = hl.head_ids[hl.head_row_node].astype(np.int32)
        head_src = np.full((d, max(rows_dev, 1), max(w, 1)), n_pad, np.int32)
        head_node = np.zeros((d, max(rows_dev, 1)), np.int32)
        for i in range(d):
            lo, hi = min(i * rows_dev, rows), min((i + 1) * rows_dev, rows)
            head_src[i, : hi - lo, :w] = hsrc_g[lo:hi]
            head_node[i, : hi - lo] = hnode_g[lo:hi]
        # tail: equal contiguous slices of the tail edge array, 'edges'
        # style (pad src=0 dst=n_pad-1 masked by valid)
        e_tail = hl.tail_src.shape[0]
        cap_t = e_dev * d
        src = np.zeros(cap_t, np.int32)
        dst = np.full(cap_t, n_pad - 1, np.int32)
        valid = np.zeros(cap_t, dtype)
        src[:e_tail] = hl.tail_src
        dst[:e_tail] = hl.tail_dst
        valid[:e_tail] = 1.0
        inv = np.zeros(n_pad, dtype)
        inv[:n] = inv_g
        dangling = np.zeros(n_pad, dtype)
        dangling[:n] = dang_g
        return ShardedGraph(
            strategy, n, n_pad, block,
            src.reshape(d, e_dev), dst.reshape(d, e_dev),
            valid.reshape(d, e_dev), inv, dangling, pad_frac,
            np.arange(n, dtype=np.int64), np.zeros((d, 1), np.int32),
            head_src=head_src, head_node=head_node,
        )

    if strategy in ("src", "src_ring"):
        # Push layout: device i owns SOURCE block [i*block, (i+1)*block) —
        # its rank shard and its nodes' out-edges.  Contributions are
        # computed from the local rank block alone (the per-edge gather
        # reads a 1/D-sized table), each device segment-sums its edges into
        # a full [n_pad] per-destination partial, and one reduce-scatter
        # (psum_scatter, or the explicit ppermute ring under 'src_ring')
        # both combines and re-shards it.  Hub-heavy *in*-degree (the
        # power-law axis of web graphs) cannot imbalance this layout: edges
        # follow their source, and out-degree is the bounded one.
        owner = graph.src // block
        order = np.lexsort((graph.dst, owner))  # by device, then dst-sorted
        src_o = graph.src[order]
        dst_o = graph.dst[order]
        ew_o = ew[order] if weighted else None
        per = plan.per
        starts = np.concatenate([[0], np.cumsum(per)])
        src_l = np.zeros((d, e_dev), np.int32)
        dst2 = np.full((d, e_dev), n_pad - 1, np.int32)  # pad keeps dst sorted
        valid = np.zeros((d, e_dev), dtype)
        for i in range(d):
            lo, hi = starts[i], starts[i + 1]
            k = hi - lo
            src_l[i, :k] = src_o[lo:hi] - i * block  # block-local sources
            dst2[i, :k] = dst_o[lo:hi]
            valid[i, :k] = ew_o[lo:hi] if weighted else 1.0
        inv = np.zeros(n_pad, dtype)
        inv[:n] = inv_g
        dangling = np.zeros(n_pad, dtype)
        dangling[:n] = dang_g
        if need_local_indptr:
            # Per-device CSR pointers over the full padded destination
            # space: each device's slice is dst-sorted, so its pointers are
            # one searchsorted over its own slice.
            local_indptr = np.empty((d, n_pad + 1), np.int32)
            for i in range(d):
                k = int(per[i])
                local_indptr[i] = np.searchsorted(
                    dst2[i, :k], np.arange(n_pad + 1)
                ).astype(np.int32)
        else:
            local_indptr = np.zeros((d, 1), np.int32)
        return ShardedGraph(strategy, n, n_pad, block, src_l, dst2, valid,
                            inv, dangling, pad_frac,
                            np.arange(n, dtype=np.int64), local_indptr)

    if strategy == "edges":
        cap = e_dev * d
        src = np.full(cap, 0, np.int32)
        dst = np.full(cap, n_pad - 1, np.int32)  # keeps dst sorted per slice tail
        valid = np.zeros(cap, dtype)
        src[:e] = graph.src
        dst[:e] = graph.dst
        valid[:e] = ew if weighted else 1.0
        inv = np.zeros(n_pad, dtype)
        inv[:n] = inv_g
        dangling = np.zeros(n_pad, dtype)
        dangling[:n] = dang_g
        dst2 = dst.reshape(d, e_dev)
        if need_local_indptr:
            # Each device's slice is a contiguous run of the global
            # dst-sorted edge array, so its CSR pointers are the global
            # ones shifted by the slice start and clamped to the slice
            # (padding slots fall outside every segment; they are zero-
            # valued anyway).  Reuses the cached graph.csr_indptr().
            g_ip = np.concatenate(
                [graph.csr_indptr(), np.full(n_pad - n, e, np.int64)]
            )
            offsets = (np.arange(d, dtype=np.int64) * e_dev)[:, None]
            local_indptr = np.clip(g_ip[None, :] - offsets, 0, e_dev).astype(np.int32)
        else:
            local_indptr = np.zeros((d, 1), np.int32)
        return ShardedGraph(strategy, n, n_pad, block,
                            src.reshape(d, e_dev), dst2,
                            valid.reshape(d, e_dev), inv, dangling, pad_frac,
                            np.arange(n, dtype=np.int64), local_indptr)

    # Node-sharded strategies: device i owns global nodes [b_i, b_{i+1})
    # (their rank shard and their in-edges, which are contiguous in the
    # dst-sorted edge array).  'nodes' picks equal-node boundaries; padding
    # each device's edge slice to the max then bears the full power-law
    # imbalance.  'nodes_balanced' picks boundaries at equal-EDGE splits
    # (node-granular, capped at 2x the equal-node block — see
    # plan_partition), evening out SpMV work instead.
    bounds_nodes = plan.bounds_nodes

    # global node id → padded slot (device i's nodes at [i*block, ...))
    node_map = np.empty(n, np.int64)
    for i in range(d):
        lo, hi = bounds_nodes[i], bounds_nodes[i + 1]
        node_map[lo:hi] = i * block + np.arange(hi - lo)

    ebounds = plan.ebounds
    src = np.zeros((d, e_dev), np.int32)
    dst_local = np.full((d, e_dev), block - 1, np.int32)
    valid = np.zeros((d, e_dev), dtype)
    src_mapped = node_map[graph.src].astype(np.int32)
    for i in range(d):
        lo, hi = ebounds[i], ebounds[i + 1]
        k = hi - lo
        src[i, :k] = src_mapped[lo:hi]
        dst_local[i, :k] = graph.dst[lo:hi] - bounds_nodes[i]
        valid[i, :k] = ew[lo:hi] if weighted else 1.0
    inv = np.zeros(n_pad, dtype)
    inv[node_map] = inv_g
    dangling = np.zeros(n_pad, dtype)
    dangling[node_map] = dang_g
    if need_local_indptr:
        # Device i's edges are global rows [ebounds[i], ebounds[i+1]) — its
        # CSR pointers are the global ones for its node range, re-based to
        # the slice; padding node slots repeat the last pointer (empty
        # segments) and padding edge slots fall outside every segment.
        g_ip = graph.csr_indptr()
        local_indptr = np.empty((d, block + 1), np.int32)
        for i in range(d):
            lo_n, hi_n = bounds_nodes[i], bounds_nodes[i + 1]
            seg = (g_ip[lo_n : hi_n + 1] - ebounds[i]).astype(np.int32)
            local_indptr[i, : seg.size] = seg
            local_indptr[i, seg.size :] = seg[-1] if seg.size else 0
    else:
        local_indptr = np.zeros((d, 1), np.int32)
    return ShardedGraph(strategy, n, n_pad, block, src, dst_local, valid,
                        inv, dangling, pad_frac, node_map, local_indptr)


def _to_padded(sg: ShardedGraph, global_vec: np.ndarray, dtype: str) -> np.ndarray:
    out = np.zeros(sg.n_pad, dtype)
    out[sg.node_map] = global_vec
    return out


def _restart_padded(sg: ShardedGraph, cfg: PageRankConfig) -> np.ndarray:
    return _to_padded(sg, ops.restart_vector(sg.n, cfg), cfg.dtype)


def make_sharded_runner(sg: ShardedGraph, cfg: PageRankConfig, mesh: Mesh):
    """Compile the sharded iteration loop.

    Returns ``run(device_arrays...) -> (ranks [n_pad], iters, delta)`` with
    ranks replicated (``edges``) or node-sharded (``nodes``) on exit.
    """
    if cfg.spark_exact:
        raise NotImplementedError(
            "spark_exact is a single-chip parity mode; run it without a mesh"
        )
    if cfg.spmv_impl not in ("segment", "cumsum", "cumsum_mxu"):
        raise NotImplementedError(
            f"spmv_impl={cfg.spmv_impl!r} is not wired into the sharded "
            "runner; use 'segment', 'cumsum' or 'cumsum_mxu' with --mesh"
        )
    if sg.strategy == "owned" and cfg.spmv_impl != "segment":
        raise NotImplementedError(
            "the owned strategy reduces its tail through the sorted "
            "segment path; use spmv_impl='segment'"
        )
    axis = mesh.axis_names[0]
    damping = cfg.damping
    total_mass = float(sg.n) if cfg.init is RankInit.ONE else 1.0
    redistribute = cfg.dangling is DanglingMode.REDISTRIBUTE
    n_pad, block = sg.n_pad, sg.block

    if sg.strategy == "owned":
        # Owned slices + sparse boundary exchange (ISSUE 15; module
        # docstring + ops/boundary.py).  Per step and per device, the ONLY
        # collectives are the log2(d) ppermute rounds of the boundary
        # butterfly and ONE [H_pad+2] psum combining the head partials —
        # whose two spare slots also carry the dangling-mass partial and
        # the PREVIOUS step's local tail delta, so neither needs a psum of
        # its own.  The global convergence gauge therefore lags one
        # iteration (a tolerance run does at most one extra step; ranks
        # are exact either way), which is the price of the
        # log2(d)-ppermute + 1-psum collective budget the registry
        # enforces.  The rank carry is a 4-tuple
        # (tail [n_pad] sharded, head [h_pad] replicated,
        #  dslot [d] sharded, gdelta [] replicated) and is DONATED.
        shard = sg.owned
        h_pad, d_ax = shard.h_pad, shard.d
        inv_d = 1.0 / d_ax  # d is pow2: exact in binary fp

        def step(carry, tsrc, tdst, tw, hsrc, hslot, hw, out_idx,
                 inv_t, dang_t, inv_h, dang_h, e_t, e_h):
            tail, head, dslot, _gd = carry
            wt = tail * inv_t  # [block] local weighted ranks
            wh = head * inv_h  # [h_pad] replicated weighted head
            btable = coll.butterfly_all_gather(
                ob.pack_boundary(wt, out_idx[0]), axis
            )  # [d*b_pad]: every shard's outgoing boundary values
            lookup = ob.boundary_lookup(wt, btable, wh)
            tail_contrib = jax.ops.segment_sum(
                lookup[tsrc[0]] * tw[0], tdst[0],
                num_segments=block, indices_are_sorted=True,
            )
            buf = jax.ops.segment_sum(
                lookup[hsrc[0]] * hw[0], hslot[0],
                num_segments=h_pad + 2, indices_are_sorted=True,
            )
            if redistribute:
                # head part is replicated: each device contributes 1/d of
                # it so the psum restores exactly one copy (d pow2 ⇒ the
                # scale round-trips exactly)
                buf = buf.at[h_pad].add(
                    jnp.sum(tail * dang_t) + jnp.sum(head * dang_h) * inv_d
                )
            buf = buf.at[h_pad + 1].add(dslot[0])
            buf = coll.psum(buf, axis)  # THE one psum of the step
            head_contrib = buf[:h_pad]
            gdelta_prev = buf[h_pad + 1]
            if redistribute:
                dmass = buf[h_pad]
                tail_contrib = tail_contrib + dmass * e_t
                head_contrib = head_contrib + dmass * e_h
            new_tail = (1.0 - damping) * total_mass * e_t + damping * tail_contrib
            new_head = (1.0 - damping) * total_mass * e_h + damping * head_contrib
            new_dslot = (
                jnp.sum(jnp.abs(new_tail - tail))
                + jnp.sum(jnp.abs(new_head - head)) * inv_d
            )[None]
            return new_tail, new_head, new_dslot, gdelta_prev

        def owned_loop(carry0, *arrays):
            return dataflow.iterate(
                lambda c: step(c, *arrays), carry0,
                iterations=cfg.iterations, tol=cfg.tol,
                delta_fn=lambda new, old: new[3],
            )

        edge_spec = P(axis, None)
        state_spec = (P(axis), P(), P(axis), P())
        mapped = shard_map(
            owned_loop,
            mesh=mesh,
            in_specs=(state_spec,
                      edge_spec, edge_spec, edge_spec,  # tail edges
                      edge_spec, edge_spec, edge_spec,  # head edges
                      edge_spec,                        # out_idx
                      P(axis), P(axis), P(), P(),       # inv/dang tail+head
                      P(axis), P()),                    # e_tail, e_head
            out_specs=(state_spec, P(), P()),
            check_vma=False,
        )
        # the owned carry is donated: per-chip state is the strategy's
        # whole point, so XLA must reuse the slice buffers in place
        # (DONATED_CALLEES row 'owned_runner'; tier-3 verifies aliasing)
        return jax.jit(mapped, donate_argnums=(0,))

    def local_reduce(per_edge, dst_row, ip_row, num_segments):
        """Per-device `reduceByKey` over its sorted edge slice: the shared
        scatter-free monotone-diff skeleton under 'cumsum'/'cumsum_mxu',
        segment_sum otherwise."""
        if cfg.spmv_impl == "cumsum":
            return ops.cumsum_diff_spmv(per_edge, ip_row)
        if cfg.spmv_impl == "cumsum_mxu":
            return ops.cumsum_diff_spmv(per_edge, ip_row,
                                        cumsum_fn=ops.cumsum_blocked)
        return jax.ops.segment_sum(
            per_edge, dst_row, num_segments=num_segments, indices_are_sorted=True
        )

    head_specs: tuple = ()
    if sg.strategy == "edges":
        # state: replicated full rank vector; one psum per iteration.
        def step(ranks, src, dst, valid, ip, inv, dang, e):
            weighted = ranks * inv
            per_edge = weighted[src[0]] * valid[0]
            partial = local_reduce(per_edge, dst[0], ip[0], n_pad)
            contribs = coll.psum(partial, axis)  # the reduceByKey, on ICI
            if redistribute:
                contribs = contribs + jnp.sum(ranks * dang) * e
            return (1.0 - damping) * total_mass * e + damping * contribs

        state_spec = P()  # replicated ranks
        vec_spec = P()  # inv/dangling/e replicated (step reads the full vectors)
        local_delta = lambda new, old: jnp.sum(jnp.abs(new - old))
    elif sg.strategy == "hybrid":
        # Degree-aware power-law layout: replicated ranks like 'edges';
        # this device's dense head rows reduce on the MXU (one matvec, no
        # scatter for the hot in-degree mass), its tail slice through the
        # sorted segment path, both into the same full-size partial — ONE
        # psum combines everything across chips.
        # a headless graph (uniform degrees) materializes one all-sentinel
        # placeholder row per device — skip the dense path entirely then,
        # not just when the padded shape is empty (it never is)
        has_head = bool((np.asarray(sg.head_src) != sg.n_pad).any())

        def step(ranks, src, dst, valid, ip, hsrc, hnode, inv, dang, e):
            weighted = ranks * inv
            per_edge = weighted[src[0]] * valid[0]
            partial = jax.ops.segment_sum(
                per_edge, dst[0], num_segments=n_pad, indices_are_sorted=True
            )
            if has_head:
                w_ext = jnp.concatenate(
                    [weighted, jnp.zeros(1, weighted.dtype)]
                )
                row_sums = ops.hybrid_rowsum(w_ext[hsrc[0]])
                partial = partial.at[hnode[0]].add(row_sums)
            contribs = coll.psum(partial, axis)
            if redistribute:
                contribs = contribs + jnp.sum(ranks * dang) * e
            return (1.0 - damping) * total_mass * e + damping * contribs

        head_specs = (P(axis, None, None), P(axis, None))
        state_spec = P()
        vec_spec = P()
        local_delta = lambda new, old: jnp.sum(jnp.abs(new - old))
    elif sg.strategy in ("src", "src_ring"):
        # Push layout: gather from the LOCAL rank block only, segment-sum
        # into a full per-destination partial, then one reduce-scatter both
        # combines across chips and keeps only this device's block — half
        # the bytes of the 'edges' psum (no re-broadcast leg), and unlike
        # 'nodes' the per-edge gather never touches a gathered [n_pad]
        # vector.  'src_ring' runs the same exchange as an explicit
        # ppermute ring (SURVEY.md §2.3 edge-cut row; §5.8).
        exchange = (coll.ring_reduce_scatter if sg.strategy == "src_ring"
                    else coll.reduce_scatter)

        def step(ranks_b, src, dst, valid, ip, inv_b, dang_b, e_b):
            weighted_b = ranks_b * inv_b  # [block], local
            per_edge = weighted_b[src[0]] * valid[0]
            partial = local_reduce(per_edge, dst[0], ip[0], n_pad)
            contrib_b = exchange(partial, axis)  # [block]
            if redistribute:
                dmass = coll.psum(jnp.sum(ranks_b * dang_b), axis)
                contrib_b = contrib_b + dmass * e_b
            return (1.0 - damping) * total_mass * e_b + damping * contrib_b

        state_spec = P(axis)
        vec_spec = P(axis)
        local_delta = lambda new, old: coll.psum(jnp.sum(jnp.abs(new - old)), axis)
    else:
        # state: [block] rank shard per device; inv/dangling/e are likewise
        # node-sharded (per-chip HBM holds only 1/D of every [n_pad] vector,
        # which is the whole point of this strategy); all_gather the
        # degree-weighted ranks, psum only the dangling-mass scalar.
        def step(ranks_b, src, dst_local, valid, ip, inv_b, dang_b, e_b):
            weighted_full = coll.all_gather(ranks_b * inv_b, axis)
            per_edge = weighted_full[src[0]] * valid[0]
            contrib_b = local_reduce(per_edge, dst_local[0], ip[0], block)
            if redistribute:
                dmass = coll.psum(jnp.sum(ranks_b * dang_b), axis)
                contrib_b = contrib_b + dmass * e_b
            return (1.0 - damping) * total_mass * e_b + damping * contrib_b

        state_spec = P(axis)
        vec_spec = P(axis)
        local_delta = lambda new, old: coll.psum(jnp.sum(jnp.abs(new - old)), axis)

    def loop(ranks0, *arrays):
        # one scan/while skeleton for every fixpoint in the repo: the
        # dataflow core's iterate combinator (dataflow/fixpoint.py), with
        # this strategy's collective delta as the convergence gauge
        return dataflow.iterate(
            lambda ranks: step(ranks, *arrays), ranks0,
            iterations=cfg.iterations, tol=cfg.tol, delta_fn=local_delta,
        )

    edge_spec = P(axis, None)
    mapped = shard_map(
        loop,
        mesh=mesh,
        in_specs=(state_spec, edge_spec, edge_spec, edge_spec, edge_spec,
                  *head_specs, vec_spec, vec_spec, vec_spec),
        out_specs=(state_spec, P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped)


def device_put_sharded_graph(sg: ShardedGraph, mesh: Mesh):
    axis = mesh.axis_names[0]
    esh = NamedSharding(mesh, P(axis, None))
    if sg.strategy == "owned":
        shard = sg.owned
        tsh = NamedSharding(mesh, P(axis))
        rsh = NamedSharding(mesh, P())
        return (
            jax.device_put(shard.tail_src_idx, esh),
            jax.device_put(shard.tail_dst, esh),
            jax.device_put(shard.tail_w, esh),
            jax.device_put(shard.head_src_idx, esh),
            jax.device_put(shard.head_slot, esh),
            jax.device_put(shard.head_w, esh),
            jax.device_put(shard.out_idx, esh),
            jax.device_put(shard.inv_tail, tsh),
            jax.device_put(shard.dang_tail, tsh),
            jax.device_put(shard.inv_head, rsh),
            jax.device_put(shard.dang_head, rsh),
        )
    # Node-state vectors follow the strategy: replicated under ``edges`` /
    # ``hybrid`` (the step reads the full vectors), node-sharded under
    # ``nodes`` (1/D per-chip HBM — the strategy's reason to exist).
    replicated_state = sg.strategy in ("edges", "hybrid")
    vsh = NamedSharding(mesh, P() if replicated_state else P(axis))
    out = [
        jax.device_put(sg.src, esh),
        jax.device_put(sg.dst, esh),
        jax.device_put(sg.valid, esh),
        jax.device_put(sg.local_indptr, esh),
    ]
    if sg.strategy == "hybrid":
        out.append(jax.device_put(sg.head_src,
                                  NamedSharding(mesh, P(axis, None, None))))
        out.append(jax.device_put(sg.head_node, esh))
    out.append(jax.device_put(sg.inv_outdeg, vsh))
    out.append(jax.device_put(sg.dangling, vsh))
    return tuple(out)


class _ShardedExec:
    """Everything welded to ONE mesh: the partition, the device-resident
    graph arrays, the state sharding, and the callables run_segments
    drives.  The elastic rung survives device loss by building a fresh
    instance over the surviving mesh — nothing here is mutated."""

    def __init__(self, graph: Graph, cfg: PageRankConfig, mesh: Mesh,
                 strategy: str, metrics: MetricsRecorder):
        self.mesh = mesh
        self.d = int(mesh.devices.size)
        with Timer() as t_part:
            self.sg = partition_graph(
                graph, self.d, strategy=strategy, dtype=cfg.dtype,
                need_local_indptr=(
                    cfg.spmv_impl in ("cumsum", "cumsum_mxu")
                    and strategy not in ("hybrid", "owned")
                ),
                head_coverage=cfg.head_coverage,
                head_row_width=cfg.head_row_width,
                owned_max_head=cfg.owned_max_head,
            )
            self.dev = device_put_sharded_graph(self.sg, mesh)
        # the static per-step exchange footprint: ICI bytes each device
        # sends per iteration under this partition (the sublinearity gauge
        # the MULTICHIP scale sweep + trace_diff comm gate consume)
        item = np.dtype(cfg.dtype).itemsize
        if self.sg.strategy == "owned":
            sh = self.sg.owned
            entries = ob.comm_entries_per_step(self.d, sh.b_pad, sh.h_pad)
        else:
            entries = _comm_entries(
                self.sg.strategy, self.d, self.sg.n_pad, self.sg.block
            )
        self.comm_bytes_per_step = int(entries * item)
        obs.gauge("pagerank.comm_bytes_per_step", self.comm_bytes_per_step)
        metrics.record(
            event="partition", strategy=strategy, devices=self.d,
            block=self.sg.block, edges_per_device=int(
                self.sg.owned.e_dev + self.sg.owned.he_dev
                if self.sg.strategy == "owned" else self.sg.src.shape[1]
            ),
            pad_frac=round(self.sg.pad_frac, 4), secs=t_part.elapsed,
            comm_bytes_per_step=self.comm_bytes_per_step,
        )
        axis = mesh.axis_names[0]
        self._cfg = cfg
        self._metrics = metrics
        if self.sg.strategy == "owned":
            # owned-slice state: a (tail sharded, head replicated) pair
            # behind the dataflow OwnedArray view, plus the lagged-delta
            # carry slots put_ranks adds
            shard = self.sg.owned
            self._tail_sh = NamedSharding(mesh, P(axis))
            self._repl_sh = NamedSharding(mesh, P())
            self.state_sharding = self._tail_sh
            self.olayout = OwnedArray.from_shard(
                shard, tail_sharding=self._tail_sh,
                head_sharding=self._repl_sh,
            )
            e_t, e_h = ob.split_global(
                shard, ops.restart_vector(self.sg.n, cfg), cfg.dtype
            )
            self.e_vec = (jax.device_put(e_t, self._tail_sh),
                          jax.device_put(e_h, self._repl_sh))
            self.layout = None
            return
        self.state_sharding = (
            NamedSharding(mesh, P())
            if self.sg.strategy in ("edges", "hybrid")
            else NamedSharding(mesh, P(axis))
        )
        self.e_vec = jax.device_put(_restart_padded(self.sg, cfg),
                                    self.state_sharding)
        # the dataflow partitioned-collection view of the rank state: one
        # logical [n] array behind the padded/relabeled device layout
        self.layout = PartitionedArray.from_plan(
            self.sg.n, self.sg.n_pad, self.sg.node_map, self.state_sharding
        )

    def make_runner(self, seg_cfg: PageRankConfig):
        return make_sharded_runner(self.sg, seg_cfg, self.mesh)

    def invoke(self, runner, rd):
        if self.sg.strategy == "owned":
            # The owned carry is DONATED: the delta fetch gets its own
            # guarded site so a transient sync failure re-pulls the live
            # OUTPUT scalar instead of letting the segment site's retry
            # re-dispatch into the consumed carry (models/pagerank.py's
            # pagerank_delta_sync discipline).
            owned_runner = runner
            rd, iters, delta = owned_runner(rd, *self.dev, *self.e_vec)
            with obs.span("pagerank.delta_sync"):
                delta = float(rx.device_get(
                    delta, site="pagerank_delta_sync",
                    metrics=self._metrics,
                    checkpoint_dir=self._cfg.checkpoint_dir,
                ))
            return rd, iters, delta
        rd, iters, delta = runner(rd, *self.dev, self.e_vec)
        delta = float(delta)  # scalar fetch is the only reliable device sync
        return rd, iters, delta

    def put_ranks(self, ranks_g: np.ndarray):
        """Global [n] ranks -> padded, sharded device state."""
        if self.sg.strategy == "owned":
            arr = self.olayout.put(ranks_g, self._cfg.dtype)
            # lagged-delta slots start at +inf so the gauge cannot read
            # "converged" before the first real global delta arrives
            dslot = jax.device_put(
                np.full(self.d, np.inf, self._cfg.dtype), self._tail_sh
            )
            gdelta = jax.device_put(
                np.asarray(np.inf, self._cfg.dtype), self._repl_sh
            )
            return (arr.tail, arr.head, dslot, gdelta)
        return self.layout.put(ranks_g, self._cfg.dtype).value

    def extract_np(self, rd) -> np.ndarray:
        """Padded device state -> global [n] ranks (checkpoint payload)."""
        with obs.span("pagerank.ckpt_pull"):
            if self.sg.strategy == "owned":
                return self.olayout.with_value(rd[0], rd[1]).pull(
                    site="pagerank_ckpt_pull", metrics=self._metrics,
                    checkpoint_dir=self._cfg.checkpoint_dir,
                )
            return self.layout.with_value(rd).pull(
                site="pagerank_ckpt_pull", metrics=self._metrics,
                checkpoint_dir=self._cfg.checkpoint_dir,
            )


def _make_elastic_rebuild(graph: Graph, cfg: PageRankConfig, strategy: str,
                          metrics: MetricsRecorder, exec_box: dict):
    """The mesh-shrink rung for run_segments (driver.ElasticResult
    contract): salvage the global ranks, checkpoint them, rebuild the mesh
    over the surviving devices (the ``nodes_balanced`` planner re-balances
    its edge splits for the new count), and rerun the failed segment with
    zero recomputed *committed* iterations."""

    def rebuild(exc, ranks_dev, done, seg_cfg):
        if not elastic.enabled() or not elastic.is_device_loss(exc):
            raise exc
        idx = elastic.device_index(exc)
        if idx is not None:
            elastic.health().mark_lost(idx)
        old = exec_box["exec"]
        # (1) salvage state at the last committed iteration: live buffers
        # first (survivor shards are usually intact), else the newest
        # checkpoint — both carry the logical [n] ranks, so they read the
        # same across mesh shapes.  A FURTHER device loss surfacing inside
        # the salvage pull itself is acknowledged and the pull retried —
        # each lap must mark a NEW device, so a genuinely dead pull falls
        # through to the checkpoint after at most one lap per lost device.
        while True:
            try:
                ranks_g, at_iter = old.extract_np(ranks_dev), done
                break
            except Exception as exc_s:
                lost_s = elastic.unwrap_device_loss(exc_s)
                idx_s = (elastic.device_index(lost_s)
                         if lost_s is not None else None)
                if idx_s is not None and elastic.health().mark_lost(idx_s):
                    exc = lost_s  # the newest loss is what the shrink blames
                    continue
                latest = (ckpt.latest_checkpoint(cfg.checkpoint_dir)
                          if cfg.checkpoint_dir else None)
                if latest is None:
                    raise exc
                step, arrays, _ = ckpt.load_checkpoint(
                    latest, cfg.config_hash()
                )
                ranks_g, at_iter = arrays["ranks"], int(step)
                break
        if cfg.checkpoint_dir:
            ckpt.save_checkpoint(
                cfg.checkpoint_dir, at_iter, {"ranks": ranks_g},
                cfg.config_hash(), extra={"devices": old.d},
            )
        # (2)-(4) shrink / rebuild / rerun — as a LOOP, because a second
        # device can die while the rerun itself is in flight (the elastic
        # gap, ISSUE 8): the rerun runs as one chaos-hooked attempt with
        # no exhaustion of its own, and a further loss re-enters this
        # ladder — re-plan from the already-shrunk mesh — instead of
        # surfacing as ResilienceExhausted.  Committed iterations
        # (< at_iter) are never recomputed on any lap.
        devices = list(old.mesh.devices.flat)
        axis = old.mesh.axis_names[0]
        todo2 = done - at_iter + seg_cfg.iterations
        seg_cfg2 = dataclasses.replace(seg_cfg, iterations=todo2)
        while True:
            plan = elastic.plan_shrink(devices)
            if plan is None:
                raise exc
            with elastic.publish_shrink("pagerank_step", plan, exc, metrics):
                # keep the dying mesh's axis name: a caller-provided mesh
                # may not be named NODES_AXIS, and the runner/shardings
                # are built from whatever the mesh declares
                new_mesh = rebuild_mesh(plan.devices, axis)
                # repartition for the survivors
                new = _ShardedExec(graph, cfg, new_mesh, strategy, metrics)
                rd2 = new.put_ranks(ranks_g)
            try:
                rd2, iters, delta = rx.attempt_once(
                    lambda n=new, r=rd2, c=seg_cfg2: n.invoke(
                        n.make_runner(c), r
                    ),
                    site="pagerank_elastic_rerun",
                )
                break
            except Exception as exc2:  # noqa: BLE001 — re-entry filter below
                lost = elastic.unwrap_device_loss(exc2)
                if lost is None:
                    raise
                idx2 = elastic.device_index(lost)
                if idx2 is not None:
                    elastic.health().mark_lost(idx2)
                exc = lost
                devices = list(new_mesh.devices.flat)
        exec_box["exec"] = new
        effective = at_iter + int(iters) - done
        return driver.ElasticResult(
            rd2, effective, delta, new.make_runner, new.invoke,
            new.extract_np, {"devices": new.d},
        )

    return rebuild


def run_pagerank_sharded(
    graph: Graph,
    cfg: PageRankConfig,
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    strategy: str = "edges",
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> PageRankResult:
    """Sharded counterpart of models.pagerank.run_pagerank — same semantics
    flags, same checkpoint segments, ranks bit-comparable across device
    counts up to float reduction order (chip-count invariance is pinned by
    tests/test_parallel.py).

    Device loss no longer aborts the run: the elastic rung (resilience/
    elastic.py) shrinks the mesh onto the surviving devices, repartitions,
    and resumes — falling through to ``ResilienceExhausted`` + checkpoint
    only when nothing survives or ``GRAFT_ELASTIC=0``."""
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, NODES_AXIS)
    d = mesh.devices.size
    if graph.n_nodes == 0:
        return PageRankResult(np.zeros(0, cfg.dtype), 0, 0.0, metrics)
    if strategy == "auto":
        strategy = auto_select_strategy(
            graph, d, dtype=cfg.dtype,
            head_coverage=cfg.head_coverage,
            head_row_width=cfg.head_row_width,
        )
        metrics.record(event="auto_strategy", chosen=strategy, devices=d)
    cfg = driver.resolve_personalize(graph, cfg)

    exec_ = _ShardedExec(graph, cfg, mesh, strategy, metrics)
    ranks_g = ops.init_ranks(exec_.sg.n, cfg)
    start_iter = (
        driver.resume_from_checkpoint(cfg, metrics, ranks_g, n=exec_.sg.n)
        if resume else 0
    )
    ranks_dev = exec_.put_ranks(ranks_g)

    # No make_cpu_invoke here: the compiled program is welded to the mesh
    # (collectives over its axis), so there is no single-device re-lowering
    # of the SAME program to degrade to.  The elastic rung is the sharded
    # degradation path: rebuild over survivors down to a 1-device mesh
    # (which the CPU backend can host when the accelerator pool is gone).
    exec_box = {"exec": exec_}
    ranks_dev, done, last_delta = driver.run_segments(
        cfg, metrics, ranks_dev, start_iter,
        make_runner=exec_.make_runner,
        invoke=exec_.invoke,
        extract_np=exec_.extract_np,
        extra_metrics={"devices": d},
        elastic_rebuild=_make_elastic_rebuild(
            graph, cfg, strategy, metrics, exec_box
        ),
    )
    # Device loss FIRST surfacing at the result pull (no segment dispatch
    # left to catch it) used to exhaust the ladder; this rung routes the
    # pull through the same elastic shrink: salvage the newest checkpoint
    # (the live buffers died with the device), rebuild over the survivors,
    # re-run the uncommitted iterations there, and pull from the rebuilt
    # mesh.  The rung swaps exec_box so the node_map below matches the
    # layout the returned padded ranks were produced in.
    def pull_rebuild(exc):
        if not elastic.enabled() or not elastic.is_device_loss(exc):
            raise exc
        idx = elastic.device_index(exc)
        if idx is not None:
            elastic.health().mark_lost(idx)
        old = exec_box["exec"]
        at_iter, ranks_g = 0, ops.init_ranks(old.sg.n, cfg)
        if cfg.checkpoint_dir:
            latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
            if latest is not None:
                step, arrays, _ = ckpt.load_checkpoint(latest, cfg.config_hash())
                at_iter, ranks_g = int(step), arrays["ranks"]
        devices = list(old.mesh.devices.flat)
        axis = old.mesh.axis_names[0]
        todo = done - at_iter
        seg_cfg = dataclasses.replace(
            cfg, iterations=todo, checkpoint_every=0, checkpoint_dir=None
        )
        # loop for the same reason as the segment rung: a second loss
        # during the re-run of the uncommitted span re-enters the ladder
        # (re-plan from the shrunk mesh) instead of exhausting
        while True:
            plan = elastic.plan_shrink(devices)
            if plan is None:
                raise exc
            with elastic.publish_shrink(
                "pagerank_result_pull", plan, exc, metrics
            ):
                new_mesh = rebuild_mesh(plan.devices, axis)
                new = _ShardedExec(graph, cfg, new_mesh, strategy, metrics)
                rd2 = new.put_ranks(ranks_g)
            if todo <= 0:
                break
            try:
                rd2, _, _ = rx.attempt_once(
                    lambda n=new, r=rd2: n.invoke(n.make_runner(seg_cfg), r),
                    site="pagerank_elastic_rerun",
                )
                break
            except Exception as exc2:  # noqa: BLE001 — re-entry filter below
                lost = elastic.unwrap_device_loss(exc2)
                if lost is None:
                    raise
                idx2 = elastic.device_index(lost)
                if idx2 is not None:
                    elastic.health().mark_lost(idx2)
                exc = lost
                devices = list(new_mesh.devices.flat)
        exec_box["exec"] = new
        # same site: chaos's device_lost is gated on the health registry,
        # so the acknowledged loss cannot re-fire here
        with obs.span("pagerank.result_pull_rebuilt"):
            return rx.device_get(
                (rd2[0], rd2[1]) if strategy == "owned" else rd2,
                site="pagerank_result_pull", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )

    with obs.span("pagerank.result_pull"):
        # owned state is a (tail, head, dslot, gdelta) carry: only the
        # two rank components cross D2H — the delta slots are scratch
        pull_view = (
            (ranks_dev[0], ranks_dev[1]) if strategy == "owned"
            else ranks_dev
        )
        ranks_np = rx.device_get(
            pull_view, site="pagerank_result_pull", metrics=metrics,
            checkpoint_dir=cfg.checkpoint_dir,
            fallbacks=[(None, pull_rebuild)],
        )
    exec_ = exec_box["exec"]  # a rebuild rung may have swapped it
    if strategy == "owned":
        ranks_final = ob.merge_global(
            exec_.sg.owned, ranks_np[0], ranks_np[1]
        )
    else:
        ranks_final = ranks_np[exec_.sg.node_map]
    return PageRankResult(
        ranks=ranks_final, iterations=done,
        l1_delta=last_delta, metrics=metrics,
    )
