"""Sharded graph workloads on owned slices (ISSUE 15 satellite): HITS and
connected components reuse the ``owned`` partition machinery through the
dataflow layer; batched personalized PageRank shards its QUERY axis.

HITS and CC both pull along BOTH edge directions (a reverse combine the
dst-sorted layout cannot serve), so each builds TWO boundary-exchange
layouts over ONE shared node ownership: the forward layout on the graph
itself and the reverse layout on the transposed graph under the SAME tail
bounds (``ops.boundary.plan_owned(bounds=...)``) — every node's state
lives in exactly one owned slice, and each direction exchanges only its
own cut.  Neither workload peels a hub head (``max_head=0``): CC's
combine is ``min`` (no psum can serve a replicated head) and HITS's
normalization already costs two ``pmax`` per step, so the heads would buy
nothing — per-step collectives are the two boundary butterflies plus the
norm/convergence reductions, all O(boundary), never O(n).

PPR is different: the graph is small enough to replicate (it is the
single-chip workload's operand), and the SCALE axis is the query batch —
so ``run_ppr_sharded`` shards the ``[B, n]`` teleport/rank matrices along
the mesh's data axis and runs the UNCHANGED ``dataflow.ppr`` batch
runner under GSPMD (the registered ``dataflow_ppr_batch`` contract covers
the program; sharding is an input property, not a new program).

Equivalence bars (tests/test_owned.py): HITS hubs/authorities and CC
labels match their single-chip oracles at 1e-6 (CC exactly); PPR matches
the single-chip batch runner at 1e-9 in f64.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import (
    components as cc,
)
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dataflow
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import hits as hits_mod
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import ppr as ppr_mod
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.partition import (
    OwnedArray,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
    put_graph_for,
)
from page_rank_and_tfidf_using_apache_spark_tpu.ops import boundary as ob
from page_rank_and_tfidf_using_apache_spark_tpu.parallel import collectives as coll
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.compat import shard_map
from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
    DATA_AXIS,
    NODES_AXIS,
    make_mesh,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    ComponentsConfig,
    HitsConfig,
    PageRankConfig,
    ensure_dtype_support,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
    MetricsRecorder,
    Timer,
)


def transpose_graph(graph: Graph) -> Graph:
    """The reversed edge set as a dst-sorted :class:`Graph` over the SAME
    compacted node ids — the reverse-direction pull of HITS/CC becomes a
    forward pull on this view.  (``from_edges`` would re-compact ids and
    could drop edgeless nodes; this keeps the node space aligned.)"""
    order = np.lexsort((graph.dst, graph.src))  # new (dst, src) = (src, dst)
    return Graph(
        n_nodes=graph.n_nodes,
        src=graph.dst[order].astype(np.int32),
        dst=graph.src[order].astype(np.int32),
        out_degree=np.bincount(
            graph.dst, minlength=graph.n_nodes
        ).astype(np.int32),
        node_ids=graph.node_ids,
        weight=graph.weight[order] if graph.weight is not None else None,
    )


def build_owned_pair(
    graph: Graph, n_devices: int, dtype: str
) -> tuple[ob.OwnedShard, ob.OwnedShard]:
    """(forward, reverse) owned shards over ONE shared node ownership:
    the forward plan picks the tail bounds (headless — see module
    docstring), the reverse plan inherits them on the transposed graph."""
    tg = transpose_graph(graph)
    fwd_plan = ob.plan_owned(graph, n_devices, max_head=0)
    rev_plan = ob.plan_owned(
        tg, n_devices, max_head=0,
        head_ids=fwd_plan.head_ids, bounds=fwd_plan.bounds,
    )
    return (ob.build_owned_shard(graph, fwd_plan, dtype),
            ob.build_owned_shard(tg, rev_plan, dtype))


def _edge_args(shard: ob.OwnedShard):
    """The per-direction device operands of a headless owned exchange."""
    return (shard.tail_src_idx, shard.tail_dst, shard.tail_w, shard.out_idx)


def _device_put_pair(sf: ob.OwnedShard, sr: ob.OwnedShard, mesh: Mesh):
    esh = NamedSharding(mesh, P(mesh.axis_names[0], None))
    return tuple(
        jax.device_put(a, esh) for a in (*_edge_args(sf), *_edge_args(sr))
    )


# ------------------------------------------------------------------- HITS


def make_hits_sharded_runner(sf: ob.OwnedShard, sr: ob.OwnedShard,
                             cfg: HitsConfig, mesh: Mesh):
    """Compile the owned HITS fixpoint: ``run((hub, auth), fwd..., rev...)
    -> ((hub, auth), iters, delta)`` — per step, one boundary butterfly
    per direction, one ``pmax`` per normalization, and the convergence
    psum; every collective O(boundary)/O(1), never O(n)."""
    axis = mesh.axis_names[0]
    block = sf.block

    def step(ha, fsrc, fdst, fw, fout, rsrc, rdst, rw, rout):
        hub, auth = ha
        bt = coll.butterfly_all_gather(
            ob.pack_boundary(hub, fout[0]), axis
        )
        lk = ob.boundary_lookup(hub, bt, jnp.zeros(sf.h_pad, hub.dtype))
        auth_raw = jax.ops.segment_sum(
            lk[fsrc[0]] * fw[0], fdst[0],
            num_segments=block, indices_are_sorted=True,
        )
        amax = coll.pmax(jnp.max(auth_raw), axis)
        auth_n = auth_raw / jnp.maximum(amax, 1e-30)
        bt2 = coll.butterfly_all_gather(
            ob.pack_boundary(auth_n, rout[0]), axis
        )
        lk2 = ob.boundary_lookup(auth_n, bt2, jnp.zeros(sr.h_pad, hub.dtype))
        hub_raw = jax.ops.segment_sum(
            lk2[rsrc[0]] * rw[0], rdst[0],
            num_segments=block, indices_are_sorted=True,
        )
        hmax = coll.pmax(jnp.max(hub_raw), axis)
        hub_n = hub_raw / jnp.maximum(hmax, 1e-30)
        return (hub_n, auth_n)

    def loop(ha0, *arrays):
        return dataflow.iterate(
            lambda ha: step(ha, *arrays), ha0,
            iterations=cfg.iterations, tol=cfg.tol,
            delta_fn=lambda new, old: coll.psum(
                jnp.sum(jnp.abs(new[0] - old[0])), axis
            ),
        )

    e = P(axis, None)
    state = (P(axis), P(axis))
    mapped = shard_map(
        loop, mesh=mesh,
        in_specs=(state, e, e, e, e, e, e, e, e),
        out_specs=(state, P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped)


def run_hits_sharded(
    graph: Graph,
    cfg: HitsConfig = HitsConfig(),
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    metrics: MetricsRecorder | None = None,
) -> hits_mod.HitsResult:
    """Sharded counterpart of ``dataflow.hits.run_hits`` on owned slices —
    same networkx-parity iteration, hubs/authorities each held only by
    their owner, pinned against the single-chip oracle at 1e-6."""
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, NODES_AXIS)
    d = int(mesh.devices.size)
    n = graph.n_nodes
    if n == 0:
        z = np.zeros(0, cfg.dtype)
        return hits_mod.HitsResult(z, z, 0, 0.0, metrics)

    with Timer() as t_part:
        sf, sr = build_owned_pair(graph, d, cfg.dtype)
        dev = _device_put_pair(sf, sr, mesh)
    metrics.record(event="partition", strategy="owned", workload="hits",
                   devices=d, block=sf.block,
                   pad_frac=round(
                       (d * sf.e_dev - graph.n_edges)
                       / max(d * sf.e_dev, 1), 4),
                   secs=t_part.elapsed)
    tail_sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    layout = OwnedArray.from_shard(
        sf, tail_sharding=tail_sh, head_sharding=NamedSharding(mesh, P())
    )
    init = np.full(n, 1.0 / n, cfg.dtype)
    hub0 = layout.put(init, cfg.dtype)
    auth0 = layout.put(init, cfg.dtype)

    runner = make_hits_sharded_runner(sf, sr, cfg, mesh)
    with obs.span("hits.sharded", devices=d, n=n):
        (hub_d, auth_d), iters, delta = runner((hub0.tail, auth0.tail), *dev)
        delta = float(delta)  # scalar fetch is the only reliable device sync
        with obs.span("hits.result_pull"):
            hubs = layout.with_value(hub_d, hub0.head).pull(
                site="hits_result_pull", metrics=metrics,
            )
            auths = layout.with_value(auth_d, auth0.head).pull(
                site="hits_result_pull", metrics=metrics,
            )
    hs, as_ = float(hubs.sum()), float(auths.sum())
    hubs = hubs / hs if hs > 0 else hubs
    auths = auths / as_ if as_ > 0 else auths
    metrics.scalar("iterations", int(iters))
    return hits_mod.HitsResult(hubs=hubs, authorities=auths,
                               iterations=int(iters), l1_delta=delta,
                               metrics=metrics)


# ------------------------------------------------- connected components


def make_components_sharded_runner(sf: ob.OwnedShard, sr: ob.OwnedShard,
                                   cfg: ComponentsConfig, mesh: Mesh):
    """Compile the owned min-label fixpoint: both directions' boundary
    labels arrive through the butterflies, the combine is a sorted
    ``segment_min`` per direction, and the changed-label count converges
    through one psum — the padding sentinel is the int32 max, so pads are
    ``min``-neutral by value instead of by mask."""
    import jax.ops  # noqa: F401  (segment_min lives under jax.ops)

    axis = mesh.axis_names[0]
    block = sf.block
    big = jnp.iinfo(jnp.int32).max

    def step(labels, fsrc, fdst, rsrc, rdst, fout, rout):
        bt = coll.butterfly_all_gather(
            ob.pack_boundary(labels, fout[0]), axis
        )
        lk = ob.boundary_lookup(
            labels, bt, jnp.full(sf.h_pad, big, labels.dtype), fill=big
        )
        incoming = jax.ops.segment_min(
            lk[fsrc[0]], fdst[0],
            num_segments=block, indices_are_sorted=True,
        )
        bt2 = coll.butterfly_all_gather(
            ob.pack_boundary(labels, rout[0]), axis
        )
        lk2 = ob.boundary_lookup(
            labels, bt2, jnp.full(sr.h_pad, big, labels.dtype), fill=big
        )
        outgoing = jax.ops.segment_min(
            lk2[rsrc[0]], rdst[0],
            num_segments=block, indices_are_sorted=True,
        )
        return jnp.minimum(labels, jnp.minimum(incoming, outgoing))

    def loop(labels0, *arrays):
        return dataflow.iterate(
            lambda lab: step(lab, *arrays), labels0,
            iterations=cfg.iterations, tol=cfg.tol,
            delta_fn=lambda new, old: coll.psum(
                jnp.sum((new != old).astype(jnp.float32)), axis
            ),
        )

    e = P(axis, None)
    mapped = shard_map(
        loop, mesh=mesh,
        in_specs=(P(axis), e, e, e, e, e, e),
        out_specs=(P(axis), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped)


def run_components_sharded(
    graph: Graph,
    cfg: ComponentsConfig = ComponentsConfig(),
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    metrics: MetricsRecorder | None = None,
) -> cc.ComponentsResult:
    """Sharded counterpart of ``dataflow.components.run_components`` on
    owned label slices — labels match the single-chip run EXACTLY (min is
    order-free), so the oracle pin is equality, not a tolerance."""
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, NODES_AXIS)
    d = int(mesh.devices.size)
    n = graph.n_nodes
    if n == 0:
        return cc.ComponentsResult(np.zeros(0, np.int32), 0, 0, metrics)

    with Timer() as t_part:
        sf, sr = build_owned_pair(graph, d, "float32")
        dev = _device_put_pair(sf, sr, mesh)
    metrics.record(event="partition", strategy="owned", workload="cc",
                   devices=d, block=sf.block, secs=t_part.elapsed)
    tail_sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    layout = OwnedArray.from_shard(
        sf, tail_sharding=tail_sh, head_sharding=NamedSharding(mesh, P())
    )
    lab0 = layout.put(np.arange(n, dtype=np.int32), np.int32)

    # the min-combine reads labels, never edge weights: drop the weight
    # coefficient arrays from the operand tuple
    fsrc, fdst, _fw, fout = dev[0], dev[1], dev[2], dev[3]
    rsrc, rdst, _rw, rout = dev[4], dev[5], dev[6], dev[7]
    runner = make_components_sharded_runner(sf, sr, cfg, mesh)
    with obs.span("cc.sharded", devices=d, n=n):
        lab_d, iters, changed = runner(
            lab0.tail, fsrc, fdst, rsrc, rdst, fout, rout
        )
        changed = float(changed)  # scalar fetch syncs the dispatch
        with obs.span("cc.result_pull"):
            labels = layout.with_value(lab_d, lab0.head).pull(
                site="cc_result_pull", metrics=metrics,
            )
    converged = changed <= cfg.tol
    if not converged:
        metrics.record(event="cc_not_converged", iterations=int(iters),
                       still_changing=int(changed))
    n_components = int(np.unique(labels).shape[0])
    metrics.scalar("n_components", n_components)
    return cc.ComponentsResult(labels=labels.astype(np.int32),
                               n_components=n_components,
                               iterations=int(iters), metrics=metrics,
                               converged=converged)


# --------------------------------------------- PPR: sharded query axis


def run_ppr_sharded(
    graph: Graph,
    cfg: PageRankConfig,
    queries,
    *,
    n_devices: int | None = None,
    mesh: Mesh | None = None,
    metrics: MetricsRecorder | None = None,
) -> ppr_mod.PprBatchResult:
    """Batched personalized PageRank with the QUERY axis sharded: the
    ``[B, n]`` teleport matrix and rank carry split across the mesh's
    data axis (B padded to a device multiple by repeating the last
    query), the graph operands replicated, and the UNCHANGED
    ``dataflow.ppr`` batch runner partitioned by GSPMD — queries are
    embarrassingly parallel, so the only cross-chip traffic is the
    worst-query convergence max."""
    ensure_dtype_support(cfg.dtype)
    if cfg.personalize is not None:
        raise ValueError("run_ppr_sharded takes queries=, not cfg.personalize")
    if not queries:
        raise ValueError("need at least one personalization query")
    metrics = metrics or MetricsRecorder()
    if mesh is None:
        mesh = make_mesh(n_devices, DATA_AXIS)
    axis = mesh.axis_names[0]
    d = int(mesh.devices.size)
    n = graph.n_nodes
    b = len(queries)
    b_pad = -(-b // d) * d
    queries_p = list(queries) + [queries[-1]] * (b_pad - b)
    metrics.record(event="ppr_sharded", queries=b, batch_pad=b_pad,
                   devices=d, nodes=n)

    batch_sh = NamedSharding(mesh, P(axis, None))
    repl = NamedSharding(mesh, P())
    e_dev = jax.device_put(
        ppr_mod.restart_batch(graph, cfg, queries_p).astype(cfg.dtype),
        batch_sh,
    )
    dg = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, repl), put_graph_for(graph, cfg)
    )
    ranks0 = jax.device_put(
        np.broadcast_to(
            np.asarray(ppr_mod.ops.init_ranks(n, cfg)), (b_pad, n)
        ).copy(),
        batch_sh,
    )
    runner = ppr_mod.make_ppr_batch_runner(n, cfg)
    with obs.span("ppr.sharded", devices=d, queries=b):
        rd, iters, delta = runner(dg, ranks0, e_dev)
        delta = float(delta)  # scalar fetch syncs the dispatch
        with obs.span("ppr.result_pull"):
            ranks = rx.device_get(
                rd, site="ppr_result_pull", metrics=metrics,
            )
    return ppr_mod.PprBatchResult(ranks=np.asarray(ranks)[:b],
                                  iterations=int(iters), l1_delta=delta,
                                  metrics=metrics)
