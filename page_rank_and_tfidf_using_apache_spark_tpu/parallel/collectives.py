"""Thin named wrappers over the XLA collectives this framework uses.

Reference counterpart (SURVEY.md §5.8): the sort-based shuffle + netty
transport + torrent broadcast stack under every ``reduceByKey``/``join``.
The rebuild's entire communication vocabulary is four collectives, all
compiled into the iteration program by XLA and scheduled on ICI/DCN:

- ``psum``          cross-chip combine (the shuffle-reduce; BASELINE.json:5
                    "allreduced over ICI via lax.psum")
- ``all_gather``    reassemble a sharded vector (the map-side fetch)
- ``reduce_scatter`` combine + re-shard in one step (psum that keeps only
                    your block — halves the bytes when output stays sharded)
- ``ppermute_ring`` neighbor exchange (the edge-cut / block-rotation
                    primitive for 2-D shardings, SURVEY.md §2.3)

Kept as a module so the communication surface is explicit, greppable, and
mockable in tests — not because the wrappers add logic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x: jax.Array, axis: str) -> jax.Array:
    return lax.psum(x, axis)


def all_gather(x: jax.Array, axis: str) -> jax.Array:
    """Tiled gather: [B] per device → [D*B] on every device."""
    return lax.all_gather(x, axis, tiled=True)


def reduce_scatter(x: jax.Array, axis: str) -> jax.Array:
    """[D*B] per device → summed, then each device keeps its [B] block."""
    return lax.psum_scatter(x, axis, tiled=True)


def ppermute_ring(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate block ``x`` ``shift`` steps around the mesh ring."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def ring_reduce_scatter(partial: jax.Array, axis: str) -> jax.Array:
    """Explicit ring reduce-scatter over ``ppermute``: the edge-cut exchange
    of SURVEY.md §2.3/§5.8 written out hop by hop.

    ``partial``: [D*B] per-destination partial sums on every device.  D-1
    steps; at each step the accumulating [B] block rotates one hop forward
    (i → i+1) on the ICI ring while the receiver folds in its local partial
    for that block.  Device i ends holding the complete sum for block i —
    bit-identical (up to float add order) to :func:`reduce_scatter`, which
    tests pin.  Exists as the hand-scheduled alternative so the exchange's
    per-hop structure (compute/comm overlap inside the scanned loop body)
    is explicit rather than delegated to XLA's psum_scatter lowering.
    """
    d = axis_size(axis)
    if d == 1:
        return partial
    i = lax.axis_index(axis)
    chunks = partial.reshape(d, -1)

    def chunk(c):
        return lax.dynamic_index_in_dim(chunks, c % d, 0, keepdims=False)

    # Device i seeds with its partial for block (i-1); each hop the carried
    # block index drops by one, so after D-1 hops it holds block i complete.
    acc = chunk(i - 1)

    def body(s, acc):
        acc = ppermute_ring(acc, axis, shift=1)  # receive from device i-1
        return acc + chunk(i - 2 - s)

    return lax.fori_loop(0, d - 1, body, acc)


def pmax(x: jax.Array, axis: str) -> jax.Array:
    """Cross-chip max (the normalization collective of sharded HITS)."""
    return lax.pmax(x, axis)


def butterfly_all_gather(block: jax.Array, axis: str) -> jax.Array:
    """Recursive-doubling (butterfly) all-gather over ``ppermute``: each
    device contributes its fixed-width ``[B, ...]`` block and ends holding
    the ``[D*B, ...]`` concatenation in device order — log₂(D) ``ppermute``
    rounds, round k carrying a 2^k·B payload, so total bytes sent per
    device are (D-1)·B entries, same as the tree-optimal all-gather.

    This is the ``owned`` strategy's sparse boundary exchange (ISSUE 15;
    *Sparse Allreduce*'s padded hub-set exchange expressed as the native
    backend-portable collective DrJAX motivates): the blocks are the
    fixed-width padded boundary buffers, so only cut-crossing entries — not
    the O(n) rank vector — ever cross the interconnect.

    After round k a device's filled rows are exactly its ALIGNED 2^k-row
    group (the partner's group differs in bit k, so the union stays one
    aligned block): both the send slice and the receive placement are
    ``dynamic_slice``/``dynamic_update_slice`` at traced offsets with
    static sizes, keeping every shape fixed across iterations.
    """
    d = axis_size(axis)
    if d == 1:
        return block
    i = lax.axis_index(axis)
    buf = jnp.zeros((d,) + block.shape, block.dtype)
    buf = lax.dynamic_update_index_in_dim(buf, block, i, 0)
    rounds = d.bit_length() - 1  # d is a power of two (mesh contract)
    for k in range(rounds):
        width = 1 << k
        base = (i >> k) << k  # my aligned 2^k-row group
        partner_base = base ^ width
        chunk = lax.dynamic_slice_in_dim(buf, base, width, axis=0)
        perm = [(j, j ^ width) for j in range(d)]
        recv = lax.ppermute(chunk, axis, perm)
        buf = lax.dynamic_update_slice_in_dim(buf, recv, partner_base, axis=0)
    return buf.reshape((d * block.shape[0],) + block.shape[1:])


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    # Older jax has no lax.axis_size; psum of a non-tracer constant folds
    # eagerly to ``1 * axis_size``, the canonical pmap-era idiom.
    return lax.psum(1, axis)
