"""Thin named wrappers over the XLA collectives this framework uses.

Reference counterpart (SURVEY.md §5.8): the sort-based shuffle + netty
transport + torrent broadcast stack under every ``reduceByKey``/``join``.
The rebuild's entire communication vocabulary is four collectives, all
compiled into the iteration program by XLA and scheduled on ICI/DCN:

- ``psum``          cross-chip combine (the shuffle-reduce; BASELINE.json:5
                    "allreduced over ICI via lax.psum")
- ``all_gather``    reassemble a sharded vector (the map-side fetch)
- ``reduce_scatter`` combine + re-shard in one step (psum that keeps only
                    your block — halves the bytes when output stays sharded)
- ``ppermute_ring`` neighbor exchange (the edge-cut / block-rotation
                    primitive for 2-D shardings, SURVEY.md §2.3)

Kept as a module so the communication surface is explicit, greppable, and
mockable in tests — not because the wrappers add logic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x: jax.Array, axis: str) -> jax.Array:
    return lax.psum(x, axis)


def all_gather(x: jax.Array, axis: str) -> jax.Array:
    """Tiled gather: [B] per device → [D*B] on every device."""
    return lax.all_gather(x, axis, tiled=True)


def reduce_scatter(x: jax.Array, axis: str) -> jax.Array:
    """[D*B] per device → summed, then each device keeps its [B] block."""
    return lax.psum_scatter(x, axis, tiled=True)


def ppermute_ring(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate block ``x`` ``shift`` steps around the mesh ring."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def ring_reduce_scatter(partial: jax.Array, axis: str) -> jax.Array:
    """Explicit ring reduce-scatter over ``ppermute``: the edge-cut exchange
    of SURVEY.md §2.3/§5.8 written out hop by hop.

    ``partial``: [D*B] per-destination partial sums on every device.  D-1
    steps; at each step the accumulating [B] block rotates one hop forward
    (i → i+1) on the ICI ring while the receiver folds in its local partial
    for that block.  Device i ends holding the complete sum for block i —
    bit-identical (up to float add order) to :func:`reduce_scatter`, which
    tests pin.  Exists as the hand-scheduled alternative so the exchange's
    per-hop structure (compute/comm overlap inside the scanned loop body)
    is explicit rather than delegated to XLA's psum_scatter lowering.
    """
    d = axis_size(axis)
    if d == 1:
        return partial
    i = lax.axis_index(axis)
    chunks = partial.reshape(d, -1)

    def chunk(c):
        return lax.dynamic_index_in_dim(chunks, c % d, 0, keepdims=False)

    # Device i seeds with its partial for block (i-1); each hop the carried
    # block index drops by one, so after D-1 hops it holds block i complete.
    acc = chunk(i - 1)

    def body(s, acc):
        acc = ppermute_ring(acc, axis, shift=1)  # receive from device i-1
        return acc + chunk(i - 2 - s)

    return lax.fori_loop(0, d - 1, body, acc)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    # Older jax has no lax.axis_size; psum of a non-tracer constant folds
    # eagerly to ``1 * axis_size``, the canonical pmap-era idiom.
    return lax.psum(1, axis)
