"""Elastic mesh degradation: shrink-and-resume on device loss (ISSUE 5).

Spark reschedules a lost executor's partitions onto the surviving pool;
a sharded XLA program cannot — its collectives are compiled against one
mesh, so a dead device kills every step that touches it (JAMPI, arxiv
2007.01811: a barrier-style sharded step must be *rebuilt*, not retried,
when the group shrinks).  What it CAN do — DrJAX's observation (arxiv
2403.07128) — is be re-expressed over a different leaf count without
changing semantics.  This module supplies the runtime pieces that turn
that into a degradation rung for the sharded runners:

- a process-global :class:`DeviceHealth` registry of lost logical devices
  (fed by chaos injection today, real XLA device errors in production);
- :func:`probe_devices` — a cheap per-device liveness check;
- :func:`plan_shrink` — pick the surviving devices (power-of-two shrink,
  ``parallel.mesh.shrink_devices``), name the ladder rung taken
  (``mesh_shrink`` while >1 device survives, ``single_device`` at the
  1-device end of the chain, ``cpu`` when the accelerator pool is gone
  and the CPU backend must host the 1-device mesh), or report that
  nothing survives (None -> the caller's ladder is exhausted).

The runner-side halves live next to the runners: ``parallel/
pagerank_sharded.py`` re-partitions the graph over the new mesh (the
``nodes_balanced`` planner re-balances edge splits for the surviving
device count) and ``parallel/tfidf_sharded.py`` re-slices the in-flight
super-chunk; ``models/driver.py`` orchestrates the rung inside the
segment loop.  Every shrink publishes a ``mesh.shrink`` span and ONE
``degraded`` event carrying old/new device counts, so a degraded run is
attributable from its trace artifact alone (tools/trace_report.py).

Env knob: ``GRAFT_ELASTIC`` ("0" disables the rung — device loss then
falls through to the pre-existing ladder ends: CPU re-lowering for
single-chip paths, ``ResilienceExhausted`` + checkpoint for sharded).
Rung names are declared in ``utils/config.DEGRADE_LADDER``; the
``ladder-rung-drift`` lint rule keeps declaration and implementation in
sync.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from typing import Iterator, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos


class DeviceHealth:
    """Thread-safe registry of lost *logical* device indices (positions in
    ``jax.devices()`` — the same index space the chaos grammar's
    ``device_lost@dev:K`` names)."""

    def __init__(self) -> None:
        self._lost: set[int] = set()
        self._lock = threading.Lock()

    def mark_lost(self, index: int) -> bool:
        """Record device ``index`` as dead; True if newly marked."""
        with self._lock:
            if index in self._lost:
                return False
            self._lost.add(index)
            return True

    def is_lost(self, index: int) -> bool:
        with self._lock:
            return index in self._lost

    def lost(self) -> frozenset:
        with self._lock:
            return frozenset(self._lost)

    def reset(self) -> None:
        with self._lock:
            self._lost.clear()


_health = DeviceHealth()


def health() -> DeviceHealth:
    """The process-global device-health registry."""
    return _health


def reset_health() -> None:
    """Forget all recorded losses (tests; a fresh run of a fresh process
    never needs this)."""
    _health.reset()


def enabled() -> bool:
    return os.environ.get("GRAFT_ELASTIC", "1") != "0"


# Lexical markers real XLA/PJRT runtimes put in device-loss errors, for
# the production path where the exception is not an injected
# chaos.DeviceLostError.
_DEVICE_LOSS_MARKERS = ("DEVICE_LOST", "device is lost", "device lost")


def is_device_loss(exc: BaseException) -> bool:
    if isinstance(exc, chaos.DeviceLostError):
        return True
    return any(m in str(exc) for m in _DEVICE_LOSS_MARKERS)


def unwrap_device_loss(exc: BaseException) -> BaseException | None:
    """The device-loss error carried by ``exc``: the exception itself, or
    the terminal error inside a :class:`~.executor.ResilienceExhausted`
    (an inner guarded call with no elastic rung of its own exhausts with
    the loss as its ``last_error`` — the shrink-rerun re-entry path needs
    to see through that wrapper).  None when ``exc`` is not a loss."""
    if is_device_loss(exc):
        return exc
    from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
        ResilienceExhausted,
    )

    if isinstance(exc, ResilienceExhausted) and is_device_loss(exc.last_error):
        return exc.last_error
    return None


def device_index(exc: BaseException) -> int | None:
    """The lost logical device index an error names, or None (whole-backend
    loss / no attribution — plan_shrink then relies on probing)."""
    dev = getattr(exc, "device", None)
    return int(dev) if isinstance(dev, int) else None


def probe_devices(devices: Sequence) -> list:
    """The subset of ``devices`` that are both un-marked in the health
    registry and answer a trivial put/get round-trip.  The probe is the
    production-path detector (a dead chip throws on the put); under chaos
    the registry alone decides, because simulated host devices never
    actually die."""
    import jax
    import numpy as np

    alive = []
    for d in devices:
        if _health.is_lost(d.id):
            continue
        try:
            # one scalar RTT per device, by design: the probe's entire job
            # is touching each device individually, and it runs only on
            # the (rare) shrink path, never per step
            jax.device_get(jax.device_put(np.int32(1), d))  # graftlint: disable=host-sync-in-loop
        except Exception:
            _health.mark_lost(d.id)
            continue
        alive.append(d)
    return alive


@dataclasses.dataclass(frozen=True)
class ShrinkPlan:
    """One planned mesh-shrink step: the devices the rebuilt mesh will
    span, the ladder rung this constitutes, and the old/new counts the
    ``degraded`` event and ``mesh.shrink`` span publish."""

    devices: tuple
    old_count: int
    new_count: int
    rung: str  # a utils/config.DEGRADE_LADDER member


def plan_shrink(mesh_devices: Sequence) -> ShrinkPlan | None:
    """Plan the next shrink for a mesh currently spanning ``mesh_devices``.

    Survivors are probed, truncated to a power-of-two count
    (``parallel.mesh.shrink_devices``), and — when the loss could not be
    attributed to any single device but the step keeps dying — forced to
    strictly fewer devices than before, so the ladder always makes
    progress.  With no surviving accelerator device the plan falls back to
    a 1-device mesh on the CPU backend (the ``cpu`` rung); None means not
    even that exists and the ladder is exhausted.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import mesh as pmesh

    devices = list(mesh_devices)
    old = len(devices)
    alive = probe_devices(devices)
    survivors = pmesh.shrink_devices(alive)
    if len(survivors) == old and old > 1:
        # nothing attributable died, yet the sharded step keeps failing:
        # halve anyway rather than rebuild the same mesh forever
        survivors = survivors[: pmesh.largest_pow2(old - 1)]
    if survivors:
        rung = "mesh_shrink" if len(survivors) > 1 else "single_device"
        return ShrinkPlan(tuple(survivors), old, len(survivors), rung)

    # Accelerator pool gone: host the 1-device mesh on the CPU backend.
    # Only when the dying mesh was NOT already CPU-backed — the health
    # registry indexes the default backend's devices, so a dead CPU mesh
    # has no fresh CPU pool to fall to (and the index spaces would alias).
    if any(getattr(d, "platform", None) == "cpu" for d in devices):
        return None
    import jax

    try:
        cpus = list(jax.devices("cpu"))
    except RuntimeError:
        cpus = []
    if not cpus:
        return None
    return ShrinkPlan((cpus[0],), old, 1, "cpu")


@contextlib.contextmanager
def publish_shrink(
    site: str,
    plan: ShrinkPlan,
    exc: BaseException,
    metrics=None,
) -> Iterator[None]:
    """The one shrink-event contract both sharded runners publish through:
    a ``mesh.shrink`` span wrapping the rebuild work, exactly ONE
    ``degraded`` event carrying the rung and old/new device counts (+ the
    mirrored metrics record), so trace_report's transitions and the
    ladder-rung-drift lint see an identical schema from every rung."""
    with obs.span("mesh.shrink", site=site, ladder=plan.rung,
                  devices_old=plan.old_count, devices_new=plan.new_count):
        obs.emit(
            "degraded", site=site, ladder=plan.rung,
            devices_old=plan.old_count, devices_new=plan.new_count,
            error=f"{type(exc).__name__}: {exc}"[:200],
        )
        obs.counter("degraded")
        if metrics is not None:
            metrics.record(
                event="degraded", site=site, ladder=plan.rung,
                devices_old=plan.old_count, devices_new=plan.new_count,
            )
        yield
