"""Resilient execution runtime: deterministic fault injection
(:mod:`.chaos`), a retry/deadline executor walking a declared degradation
ladder (:mod:`.executor`), elastic mesh shrink-and-resume on device loss
(:mod:`.elastic`), and the structured :class:`ResilienceExhausted` that
hands callers the checkpoint to resume from.  See README "Failure model
and recovery" for the contract."""

from page_rank_and_tfidf_using_apache_spark_tpu.resilience.chaos import (
    ChaosError,
    DeviceLostError,
    PartitionError,
    inject,
    parse_plan,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.elastic import (
    DeviceHealth,
    ShrinkPlan,
    plan_shrink,
    reset_health,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
    ResilienceExhausted,
    RetryPolicy,
    SyncDeadlineExceeded,
    block_until_ready,
    device_get,
    is_transient,
    run_guarded,
)

__all__ = [
    "ChaosError",
    "DeviceHealth",
    "DeviceLostError",
    "PartitionError",
    "ResilienceExhausted",
    "RetryPolicy",
    "ShrinkPlan",
    "SyncDeadlineExceeded",
    "block_until_ready",
    "device_get",
    "inject",
    "is_transient",
    "parse_plan",
    "plan_shrink",
    "reset_health",
    "run_guarded",
]
