"""Resilient execution runtime: deterministic fault injection
(:mod:`.chaos`), a retry/deadline executor with a CPU degradation ladder
(:mod:`.executor`), and the structured :class:`ResilienceExhausted` that
hands callers the checkpoint to resume from.  See README "Failure model
and recovery" for the contract."""

from page_rank_and_tfidf_using_apache_spark_tpu.resilience.chaos import (
    ChaosError,
    DeviceLostError,
    inject,
    parse_plan,
)
from page_rank_and_tfidf_using_apache_spark_tpu.resilience.executor import (
    ResilienceExhausted,
    RetryPolicy,
    SyncDeadlineExceeded,
    block_until_ready,
    device_get,
    is_transient,
    run_guarded,
)

__all__ = [
    "ChaosError",
    "DeviceLostError",
    "ResilienceExhausted",
    "RetryPolicy",
    "SyncDeadlineExceeded",
    "block_until_ready",
    "device_get",
    "inject",
    "is_transient",
    "parse_plan",
    "run_guarded",
]
