"""Process-level fault handling: the supervisor side of the serving
fabric (ISSUE 17).

Every rung so far degrades *inside* one process (retry → mesh_shrink →
single_device → cpu).  A replica process that is SIGKILLed — the chaos
kind ``proc_kill``, or a real OOM/segfault — is past all of them: the
recovery is a *different* process respawning it, which is exactly Spark's
driver-replaces-executor story (PAPER.md) applied to serving replicas.
This module owns that rung: a thin :class:`ProcessHandle` around
``subprocess.Popen`` (spawn / ready-handshake / graceful TERM with a
KILL deadline), and :func:`respawn`, which publishes the ``degraded``
event on the declared ``respawn`` ladder rung (``utils/config.py``
``DEGRADE_LADDER`` — the ladder-rung-drift rule audits both sides)
before bringing the replacement up.

The ready handshake is one JSON line on the child's stdout (the fabric
replica prints ``{"ready": true, "port": ..., ...}`` once it can serve):
supervisors must not route to a replica that is still mmap-loading
segments.  Stdout after the handshake keeps streaming into a drain
thread so a chatty child can never fill the pipe and wedge itself.
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import threading
import time
from typing import Any, Callable, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu import obs


class ProcessSpawnError(RuntimeError):
    """The child died (or said something unparseable) before its ready
    handshake — spawn-time failure, distinct from a crash while serving."""


class ProcessHandle:
    """One supervised child process.

    Lifecycle: ``spawn()`` forks it and waits for the one-line JSON ready
    handshake on stdout; ``alive()`` polls; ``terminate()`` is the
    graceful path (SIGTERM, bounded wait, SIGKILL only past the
    deadline); ``kill()`` is the chaos/crash path (immediate SIGKILL).
    The handle is re-spawnable: :func:`respawn` builds a fresh one from
    the same argv/env."""

    def __init__(self, argv: Sequence[str], *,
                 env: dict[str, str] | None = None,
                 ready_timeout_s: float = 60.0):
        self.argv = list(argv)
        self.env = dict(env) if env is not None else None
        self.ready_timeout_s = ready_timeout_s
        self.ready: dict[str, Any] = {}
        self.proc: subprocess.Popen | None = None
        self._drain: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def spawn(self) -> "ProcessHandle":
        """Fork the child and block for its ready handshake (one JSON
        line on stdout).  Raises :class:`ProcessSpawnError` when the
        child exits or prints garbage instead."""
        self.proc = subprocess.Popen(
            self.argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=self.env,
        )
        assert self.proc.stdout is not None
        deadline = time.monotonic() + self.ready_timeout_s
        line = ""
        while True:
            if time.monotonic() > deadline:
                self.kill()
                raise ProcessSpawnError(
                    f"no ready handshake within {self.ready_timeout_s}s: "
                    f"{self.argv!r}"
                )
            # select-bounded read: a silent-but-alive child must not wedge
            # the supervisor in a blocking readline past the deadline
            ready_r, _, _ = select.select([self.proc.stdout], [], [], 0.25)
            if not ready_r:
                if self.proc.poll() is not None:
                    raise ProcessSpawnError(
                        f"child exited rc={self.proc.returncode} before "
                        f"ready handshake: {self.argv!r}"
                    )
                continue
            line = self.proc.stdout.readline()
            if line.strip():
                break
            if not line and self.proc.poll() is not None:
                raise ProcessSpawnError(
                    f"child exited rc={self.proc.returncode} before ready "
                    f"handshake: {self.argv!r}"
                )
        try:
            self.ready = json.loads(line)
        except (json.JSONDecodeError, ValueError) as exc:
            self.kill()
            raise ProcessSpawnError(
                f"unparseable ready handshake {line!r} from {self.argv!r}"
            ) from exc
        if not self.ready.get("ready"):
            self.kill()
            raise ProcessSpawnError(
                f"child declined ready handshake: {self.ready!r}"
            )
        # keep draining stdout so the child can't block on a full pipe
        # (declared in THREAD_REGISTRY with an empty lock set: the drain
        # touches no shared mutable state)
        self._drain = threading.Thread(
            target=self._drain_stdout, name="proc-stdout-drain", daemon=True
        )
        self._drain.start()
        return self

    def _drain_stdout(self) -> None:
        assert self.proc is not None and self.proc.stdout is not None
        try:
            for _ in self.proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def returncode(self) -> int | None:
        return self.proc.returncode if self.proc is not None else None

    def terminate(self, grace_s: float = 10.0) -> int | None:
        """Graceful stop: SIGTERM, wait up to ``grace_s``, then SIGKILL.
        Returns the exit code (None if there was no process)."""
        if self.proc is None:
            return None
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self.kill()
        return self.proc.wait()

    def kill(self) -> None:
        """Immediate SIGKILL — the chaos path and the grace-expired path."""
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass
            self.proc.wait()


def respawn(
    handle: ProcessHandle,
    *,
    site: str,
    replica: int | None = None,
    reason: str | None = None,
    spawn: Callable[[], ProcessHandle] | None = None,
) -> ProcessHandle:
    """Replace a dead (or about-to-be-replaced) child with a fresh spawn
    of the same argv/env — the ``respawn`` rung of the degradation
    ladder, published BEFORE the replacement comes up so a respawn that
    itself dies still left evidence.  ``spawn`` overrides how the
    replacement is built (the fabric threads a port re-assignment in)."""
    old_pid = handle.pid
    rc = handle.returncode()
    obs.emit(
        "degraded", site=site, ladder="respawn", replica=replica,
        pid=old_pid, returncode=rc,
        error=(reason or f"process {old_pid} rc={rc}")[:200],
    )
    obs.counter("degraded")
    obs.counter("respawns")
    handle.kill()  # reap a half-dead child before replacing it
    if spawn is not None:
        return spawn()
    fresh = ProcessHandle(handle.argv, env=handle.env,
                          ready_timeout_s=handle.ready_timeout_s)
    return fresh.spawn()


def fabric_pgid_env() -> dict[str, str]:
    """Environment for fabric children: inherit, minus knobs that must
    not leak parent-scoped state into replicas (each replica gets its own
    chaos plan from the caller, not the parent's)."""
    env = dict(os.environ)
    env.pop("GRAFT_CHAOS", None)
    return env
