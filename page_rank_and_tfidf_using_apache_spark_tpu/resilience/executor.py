"""Retry/deadline executor guarding every dispatch and host-sync boundary.

Spark's resilience came from lineage recomputation; on TPU the equivalents
are (in escalation order) **retry** the failed dispatch/sync on-device,
**degrade** — shrink a sharded mesh onto the surviving devices
(resilience/elastic.py) or re-lower a single-chip segment for the CPU
backend — and finally **resume** from the last atomic checkpoint
(utils/checkpoint.py).  This module implements retry plus the generic
rung-walking (``fallbacks``), and hands the terminal state to callers as a
structured :class:`ResilienceExhausted` carrying the latest checkpoint
path.  Rung names are declared in ``utils/config.DEGRADE_LADDER``.

Every long-running path (models/driver.py segments, the streaming and
sharded TF-IDF chunk drains) routes its host round-trips through
:func:`run_guarded` or the :func:`device_get` / :func:`block_until_ready`
wrappers; the graftlint rule ``unguarded-host-sync`` keeps it that way.

Env knobs (also see README "Failure model and recovery"):

- ``GRAFT_RETRY_MAX``        max retries per guarded call (default 3)
- ``GRAFT_SYNC_DEADLINE_S``  per-call watchdog deadline in seconds;
                             0 (default) disables the watchdog thread
- ``GRAFT_BACKOFF_BASE_S``   first backoff delay (default 0.05)
- ``GRAFT_BACKOFF_MAX_S``    backoff ceiling (default 2.0)
- ``GRAFT_CHAOS``            fault-injection plan (resilience/chaos.py)

Retries are only issued for *transient* failures (injected ``ChaosError``,
a blown sync deadline, or an XLA runtime error carrying a retryable status
marker).  ``DeviceLostError`` — and transient failures that exhaust the
retry budget — fall through to the degradation ladder.  Backoff jitter is
deterministic (hash of site and attempt), so chaos tests replay exactly.

Retry safety: every guarded callable here is re-invocable — ``device_get``
re-reads live device buffers, and the compiled segment runners are
functional (same inputs in, same ranks out), so a retried dispatch cannot
double-apply work.

Telemetry (ISSUE 4): every rung publishes a structured event on the obs
bus — ``retry`` / ``backoff`` per retried attempt, ``watchdog`` when the
sync deadline fires, ``degraded`` on the CPU rung, ``exhausted`` before
raising — so a traced run's JSONL file records *which* site failed, how
many retries it ate and what each backoff cost, durably, even when the
process is later killed.  ``metrics.record`` mirrors the retry/degraded
events into the legacy per-run recorder for callers that pass one.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Callable

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import chaos
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


class SyncDeadlineExceeded(RuntimeError):
    """A guarded call blew its GRAFT_SYNC_DEADLINE_S watchdog — the
    signature of a hung host sync on a dead tunnel.  Transient: the retry
    re-issues the sync against the still-live device buffers."""


class ResilienceExhausted(RuntimeError):
    """Every rung of the ladder failed.  Carries what the caller needs to
    restart-from-snapshot: the site, the last error, and the most recent
    checkpoint path (None when the caller checkpoints nowhere)."""

    def __init__(
        self,
        site: str,
        attempts: int,
        last_error: BaseException,
        last_checkpoint: str | None,
    ):
        self.site = site
        self.attempts = attempts
        self.last_error = last_error
        self.last_checkpoint = last_checkpoint
        resume = (
            f"resume from checkpoint {last_checkpoint}"
            if last_checkpoint
            else "no checkpoint available; restart from scratch"
        )
        super().__init__(
            f"resilience exhausted at {site!r} after {attempts} attempt(s): "
            f"{type(last_error).__name__}: {last_error} — {resume}"
        )


# Status markers XLA/PJRT put in retryable runtime errors.  Lexical match on
# the message keeps this dependency-free (the exception classes moved
# between jaxlib versions).
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
)


def is_transient(exc: BaseException) -> bool:
    if isinstance(exc, (chaos.ChaosError, SyncDeadlineExceeded)):
        return True
    # A fully-exhausted inner ladder is not transient by definition — and
    # its message quotes the inner error, so the marker scan below would
    # otherwise re-classify it.  Matters for nested guards: the delta
    # fetch inside models/pagerank.py's invoke exhausts under the outer
    # pagerank_step guard, whose retry must NOT re-dispatch (the runner
    # donated its rank carry).
    if isinstance(exc, (chaos.DeviceLostError, ResilienceExhausted)):
        return False
    return any(m in str(exc) for m in _TRANSIENT_MARKERS)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    deadline_s: float = 0.0  # 0 = no watchdog thread

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            max_retries=int(os.environ.get("GRAFT_RETRY_MAX", 3)),
            backoff_base_s=float(os.environ.get("GRAFT_BACKOFF_BASE_S", 0.05)),
            backoff_max_s=float(os.environ.get("GRAFT_BACKOFF_MAX_S", 2.0)),
            deadline_s=float(os.environ.get("GRAFT_SYNC_DEADLINE_S", 0.0)),
        )


def backoff_delay(site: str, attempt: int, policy: RetryPolicy) -> float:
    """Exponential backoff with deterministic jitter: attempt k (1-based)
    waits base * 2^(k-1) * (1 + frac), frac in [0, 0.5) derived from a hash
    of (site, attempt) — decorrelates concurrent retriers without RNG state
    (chaos tests replay bit-identically)."""
    raw = policy.backoff_base_s * (2.0 ** (attempt - 1))
    h = hashlib.sha256(f"{site}:{attempt}".encode()).digest()
    frac = h[0] / 512.0  # [0, 0.498]
    return min(raw * (1.0 + frac), policy.backoff_max_s)


def _attempt(fn: Callable[[], Any], site: str, policy: RetryPolicy) -> Any:
    """One guarded attempt: chaos hook + fn, under the watchdog when a
    deadline is set.  The watchdog runs the attempt on a fresh daemon
    thread and abandons it on timeout — a thread wedged inside a dead
    device runtime cannot be killed from Python, only orphaned."""

    def watched() -> Any:
        chaos.on_call(site)
        return fn()

    if policy.deadline_s <= 0:
        return watched()

    box: dict[str, Any] = {}

    def runner() -> None:
        try:
            box["result"] = watched()
        except BaseException as exc:  # noqa: BLE001 — re-raised on the caller side
            box["error"] = exc

    t = threading.Thread(target=runner, name=f"resilience-{site}", daemon=True)
    t.start()
    t.join(policy.deadline_s)
    if t.is_alive():
        obs.emit("watchdog", site=site, deadline_s=policy.deadline_s)
        obs.counter("watchdog_fires")
        raise SyncDeadlineExceeded(
            f"guarded call at {site!r} exceeded the {policy.deadline_s}s "
            "sync deadline (hung host sync); abandoning the attempt thread"
        )
    if "error" in box:
        raise box["error"]
    return box["result"]


def attempt_once(
    fn: Callable[[], Any],
    *,
    site: str,
    policy: RetryPolicy | None = None,
) -> Any:
    """ONE chaos-hooked, watchdog-deadlined attempt with no retry loop, no
    rungs and no ``exhausted`` emission — for callers that own their
    recovery (the elastic shrink-*rerun*, which on a further device loss
    must re-enter its own ladder rather than have this layer declare
    exhaustion).  Faults propagate raw; ``fn`` must be re-invocable."""
    policy = policy or RetryPolicy.from_env()
    return _attempt(fn, site, policy)


def _retry_pause(
    site: str,
    attempts: int,
    exc: BaseException,
    policy: RetryPolicy,
    metrics: MetricsRecorder | None,
) -> None:
    """The shared between-attempts pause of both retry loops: emit the
    ``retry`` event/counter (and mirror it to the caller's metrics), sleep
    the backoff, then emit ``backoff``.  The backoff event is emitted
    AFTER the sleep: it records that the backoff completed (a kill
    mid-backoff then shows a retry with no backoff event), which is what
    distinguishes it from the retry event."""
    delay = backoff_delay(site, attempts, policy)
    err = f"{type(exc).__name__}: {exc}"[:200]
    obs.emit("retry", site=site, attempt=attempts, error=err,
             backoff_s=round(delay, 4))
    obs.counter("retries")
    if metrics is not None:
        metrics.record(event="retry", site=site, attempt=attempts,
                       error=err, backoff_s=round(delay, 4))
    time.sleep(delay)
    obs.emit("backoff", site=site, attempt=attempts, secs=round(delay, 4))
    obs.histogram("backoff_secs", delay)


def retry_transient(
    fn: Callable[[], Any],
    *,
    site: str,
    policy: RetryPolicy | None = None,
    metrics: MetricsRecorder | None = None,
) -> Any:
    """:func:`run_guarded`'s transient-retry half WITHOUT the terminal
    rung-walking or ``exhausted`` emission: transient faults retry with the
    same backoff/telemetry, but persistent faults (device loss) and an
    expired retry budget propagate RAW to the caller.

    For call sites whose recovery lives at a coarser granularity than one
    guarded call — the staged ingest pipeline (``dataflow.ingest``): a
    device loss at an H2D put on the transfer thread is handled by the
    pipeline's recovery point (tear down, shrink/salvage, re-stage from
    retained host copies), so an ``exhausted`` event here would misreport
    a recoverable loss as a dead ladder.  Same precedent as
    :func:`attempt_once` (the elastic shrink-rerun's re-entry path).
    ``fn`` must be re-invocable."""
    policy = policy or RetryPolicy.from_env()
    attempts = 0
    while True:
        attempts += 1
        try:
            return _attempt(fn, site, policy)
        except Exception as exc:
            if not is_transient(exc) or attempts > policy.max_retries:
                raise
            _retry_pause(site, attempts, exc, policy, metrics)


def run_guarded(
    fn: Callable[[], Any],
    *,
    site: str,
    policy: RetryPolicy | None = None,
    metrics: MetricsRecorder | None = None,
    checkpoint_dir: str | None = None,
    fallback: Callable[[], Any] | None = None,
    fallbacks: "list[tuple[str | None, Callable[[BaseException], Any]]] | None" = None,
) -> Any:
    """Run ``fn`` under the full degradation ladder.

    1. up to ``policy.max_retries`` retries with exponential backoff, for
       transient failures only;
    2. the ``fallbacks`` rungs in order — each a ``(ladder, fn(exc))``
       pair.  A named rung publishes the ``degraded`` event here before
       running (``ladder`` must be declared in utils/config.DEGRADE_LADDER
       — the lint gate); ``ladder=None`` hands emission to the rung
       itself, for rungs like the elastic mesh shrink that only *decide*
       whether they apply (and what they degraded to) once they inspect
       the failure.  A rung that raises passes the ladder to the next.
       ``fallback=`` is legacy sugar for one trailing no-arg ``cpu`` rung.
    3. :class:`ResilienceExhausted` carrying the latest checkpoint under
       ``checkpoint_dir`` so the caller (or the operator) can resume.

    ``fn`` must be safe to re-invoke (pure dispatch / buffer re-read).
    """
    policy = policy or RetryPolicy.from_env()
    attempts = 0
    last_exc: Exception | None = None
    while attempts <= policy.max_retries:
        attempts += 1
        try:
            return _attempt(fn, site, policy)
        # Exception, not BaseException: KeyboardInterrupt / SystemExit must
        # propagate — a Ctrl-C is an operator decision, not a device fault
        # for the ladder to "recover" from.
        except Exception as exc:
            last_exc = exc
            if not is_transient(exc):
                break
            if attempts > policy.max_retries:
                break
            _retry_pause(site, attempts, exc, policy, metrics)

    rungs = list(fallbacks or [])
    if fallback is not None:
        rungs.append(("cpu", lambda _exc, _fb=fallback: _fb()))
    for ladder, rung_fn in rungs:
        if ladder is not None:
            err = f"{type(last_exc).__name__}: {last_exc}"[:200]
            obs.emit("degraded", site=site, ladder=ladder,
                     after_attempts=attempts, error=err)
            obs.counter("degraded")
            if metrics is not None:
                metrics.record(
                    event="degraded", site=site, ladder=ladder,
                    after_attempts=attempts, error=err,
                )
        try:
            return rung_fn(last_exc)
        except Exception as exc:  # try the next rung; interrupts propagate
            last_exc = exc

    assert last_exc is not None
    last_ckpt = ckpt.latest_checkpoint(checkpoint_dir) if checkpoint_dir else None
    obs.emit(
        "exhausted", site=site, attempts=attempts,
        error=f"{type(last_exc).__name__}: {last_exc}"[:200],
        checkpoint=last_ckpt,
    )
    obs.counter("exhausted")
    raise ResilienceExhausted(site, attempts, last_exc, last_ckpt) from last_exc


def device_get(
    tree: Any,
    *,
    site: str = "device_get",
    policy: RetryPolicy | None = None,
    metrics: MetricsRecorder | None = None,
    checkpoint_dir: str | None = None,
    fallbacks: "list[tuple[str | None, Callable[[BaseException], Any]]] | None" = None,
) -> Any:
    """Guarded ``jax.device_get``: ONE batched device->host pull per call
    (keep the VERDICT r5 single-round-trip discipline), retried/deadlined
    by the executor.  Device buffers outlive a failed pull, so re-issuing
    the transfer is always safe.  ``fallbacks`` rungs (e.g. the sharded
    runners' elastic mesh shrink) apply exactly as in :func:`run_guarded`."""
    import jax

    return run_guarded(
        lambda: jax.device_get(tree), site=site, policy=policy,
        metrics=metrics, checkpoint_dir=checkpoint_dir, fallbacks=fallbacks,
    )


def block_until_ready(
    tree: Any,
    *,
    site: str = "block_until_ready",
    policy: RetryPolicy | None = None,
    metrics: MetricsRecorder | None = None,
    checkpoint_dir: str | None = None,
) -> Any:
    """Guarded ``jax.block_until_ready`` fence."""
    import jax

    return run_guarded(
        lambda: jax.block_until_ready(tree), site=site, policy=policy,
        metrics=metrics, checkpoint_dir=checkpoint_dir,
    )
