"""Deterministic fault injection for the resilience executor.

The production failure modes this repo has actually hit (BENCH_r05: the
TF-IDF streaming child dying with ``[tfidf] TIMEOUT after 420s`` at chunk
24, losing all 24 completed chunks) are transient device errors, hung
host<->device syncs on the relay tunnel, and outright device loss.  None of
them can be provoked on demand on real hardware, so recovery paths would
otherwise ship untested.  This shim injects all three deterministically at
*guarded call sites* (every host-sync / dispatch boundary routed through
``resilience.executor``), so tier-1 CPU tests can prove end-to-end recovery.

Plan specification — the ``GRAFT_CHAOS`` env var or :func:`inject`::

    GRAFT_CHAOS = "<injection>[;<injection>...]"
    <injection> = "<site>:<kind>@<when>[:<param>]"

    site   exact site name as passed to executor.run_guarded (e.g.
           "pagerank_step", "tfidf_chunk_sync"), or "*" for every site
    kind   fail  - raise ChaosError (a *transient* device error: the
                   executor retries it with backoff)
           lost  - raise DeviceLostError (*persistent*: no retry; the
                   executor degrades to the CPU ladder or raises
                   ResilienceExhausted)
           hang  - sleep <param> seconds (default 3600) before returning,
                   simulating a hung device_get; only a sync deadline
                   (GRAFT_SYNC_DEADLINE_S) interrupts it
           device_lost - kill ONE logical device: raise DeviceLostError
                   carrying ``.device = K`` on every matching guarded call
                   until the elastic runtime (resilience/elastic.py)
                   acknowledges the loss by marking device K dead — exactly
                   how a real dead chip behaves: every touch fails until
                   the scheduler stops scheduling onto it.  Spelled
                   ``device_lost@dev:K`` (K = index into jax.devices()).
           proc_kill - SIGKILL the *current process* at the site (the
                   chaos event is flushed to the trace first): a replica
                   dying mid-query or mid-hot-swap in the serving fabric.
                   Recovery belongs to a DIFFERENT process (the fabric
                   supervisor respawns; the router re-dispatches), so this
                   kind never returns.
           net_partition - raise PartitionError (a ChaosError subclass,
                   so still *transient* to the executor): the router's
                   view of an unreachable replica.  The fabric marks the
                   target suspect and retries the query on a sibling.
           net_hang - sleep <param> MILLISECONDS (default 500) before
                   returning — a slow/blackholed network hop, deliberately
                   in ms where ``hang`` is in seconds: network stalls are
                   bounded by request timeouts, not the sync watchdog.
    when   N     the Nth guarded call at this site (1-based), exactly once
           N+    every call from the Nth on
           %K    every Kth call (K, 2K, 3K, ...)
           dev   (device_lost only) every call while device <param> is
                 still considered healthy
    param  seconds for hang; MILLISECONDS for net_hang; the logical
           device index for device_lost

Examples::

    GRAFT_CHAOS="pagerank_step:fail@2"          # one transient mid-run blip
    GRAFT_CHAOS="tfidf_chunk_sync:lost@26"      # kill the 26th chunk drain
    GRAFT_CHAOS="*:fail@%5"                     # every 5th guarded call
                                                # fails once (chaos.sh)
    GRAFT_CHAOS="*:device_lost@dev:1"           # logical device 1 dies; a
                                                # sharded run must shrink
                                                # its mesh to survive

Counters are per *actual* site name and live on the installed plan, so one
plan == one deterministic schedule.  Everything is thread-safe: guarded
calls may come from the streaming prefetch machinery.
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import threading
import time

from page_rank_and_tfidf_using_apache_spark_tpu import obs


class ChaosError(RuntimeError):
    """Injected *transient* device error (stands in for the retryable
    XlaRuntimeError family: UNAVAILABLE / DEADLINE_EXCEEDED / ...)."""


class PartitionError(ChaosError):
    """Injected network partition between router and replica (kind
    ``net_partition``).  A :class:`ChaosError` subclass on purpose: to the
    retry machinery a partition is transient (the link may heal), but the
    fabric router additionally marks the target replica *suspect* so the
    very next attempt routes to a sibling instead of the black hole."""


class DeviceLostError(RuntimeError):
    """Injected *persistent* device loss — retrying on the same device
    cannot help; only degradation or restart-from-snapshot can.

    ``device`` names the lost logical device index (into ``jax.devices()``)
    when the fault targets one device (kind ``device_lost``); None means
    the whole backend is gone (kind ``lost``)."""

    def __init__(self, message: str, device: int | None = None):
        super().__init__(message)
        self.device = device


@dataclasses.dataclass(frozen=True)
class Injection:
    site: str  # exact site name or "*"
    kind: str  # "fail" | "lost" | "hang" | "device_lost" | "proc_kill" | "net_partition" | "net_hang"
    when: str  # "N" | "N+" | "%K" | "dev"
    param: float  # seconds for hang, ms for net_hang, device for device_lost

    def matches(self, site: str, count: int) -> bool:
        if self.site != "*" and self.site != site:
            return False
        w = self.when
        if w == "dev":
            # device_lost: fires on every call; gated at injection time on
            # whether the target device is still considered healthy
            return True
        if w.startswith("%"):
            k = int(w[1:])
            return k > 0 and count % k == 0
        if w.endswith("+"):
            return count >= int(w[:-1])
        return count == int(w)


def parse_plan(spec: str) -> tuple[Injection, ...]:
    """Parse a GRAFT_CHAOS spec string; raises ValueError on bad syntax."""
    out: list[Injection] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(f"bad chaos injection {raw!r}: want site:kind@when[:param]")
        site, action = parts[0], parts[1]
        if "@" not in action:
            raise ValueError(f"bad chaos injection {raw!r}: missing @when")
        kind, when = action.split("@", 1)
        if kind not in ("fail", "lost", "hang", "device_lost",
                        "proc_kill", "net_partition", "net_hang"):
            raise ValueError(f"bad chaos kind {kind!r} in {raw!r}")
        if kind == "device_lost":
            # grammar: site:device_lost@dev:K — the device index rides in
            # the param slot, and "dev" is the only legal schedule token
            if when != "dev" or len(parts) != 3 or not parts[2].isdigit():
                raise ValueError(
                    f"bad chaos injection {raw!r}: device_lost is spelled "
                    "site:device_lost@dev:<device-index>"
                )
            out.append(Injection(site=site, kind=kind, when=when,
                                 param=float(int(parts[2]))))
            continue
        m = re.fullmatch(r"%(\d+)|(\d+)\+?", when)
        if m is None or int(m.group(1) or m.group(2)) < 1:
            raise ValueError(f"bad chaos schedule {when!r} in {raw!r}")
        if len(parts) == 3:
            param = float(parts[2])
        else:
            # hang defaults to "forever" (only a deadline interrupts it);
            # net_hang to 500 ms (a stall a request timeout should absorb)
            param = 500.0 if kind == "net_hang" else 3600.0
        out.append(Injection(site=site, kind=kind, when=when, param=param))
    return tuple(out)


class ChaosPlan:
    """An installed injection schedule with per-site call counters."""

    def __init__(self, injections: tuple[Injection, ...]):
        self.injections = injections
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def call_count(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def on_call(self, site: str) -> None:
        """Record one guarded call at ``site`` and apply any matching
        injection (first match wins)."""
        with self._lock:
            count = self._counts.get(site, 0) + 1
            self._counts[site] = count
        for inj in self.injections:
            if not inj.matches(site, count):
                continue
            if inj.kind == "device_lost":
                # Fires only while the target device is still believed
                # healthy: once the elastic runtime acknowledges the loss
                # (resilience/elastic.py marks it dead and the mesh no
                # longer schedules onto it), touching the survivors
                # succeeds again.  Lazy import — elastic imports this
                # module at load time.
                from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
                    elastic,
                )

                dev = int(inj.param)
                if elastic.health().is_lost(dev):
                    continue
                obs.emit("chaos", site=site, fault=inj.kind, call=count,
                         device=dev)
                obs.counter("chaos_injections")
                raise DeviceLostError(
                    f"chaos: device {dev} lost at {site} call #{count}",
                    device=dev,
                )
            # published BEFORE the fault takes effect: the injection must be
            # on record even when it hangs or kills the run it fires in
            obs.emit("chaos", site=site, fault=inj.kind, call=count)
            obs.counter("chaos_injections")
            if inj.kind == "hang":
                time.sleep(inj.param)
                return
            if inj.kind == "net_hang":
                time.sleep(inj.param / 1000.0)
                return
            if inj.kind == "proc_kill":
                os.kill(os.getpid(), signal.SIGKILL)
                # unreachable in a real run; during tests os.kill may be
                # monkeypatched to observe the schedule without dying
                return
            if inj.kind == "net_partition":
                raise PartitionError(
                    f"chaos: partition at {site} call #{count}"
                )
            if inj.kind == "lost":
                raise DeviceLostError(
                    f"chaos: device lost at {site} call #{count}"
                )
            raise ChaosError(f"chaos: transient failure at {site} call #{count}")


# The active plan: an explicit inject() context overrides the env plan.
_lock = threading.Lock()
_installed: ChaosPlan | None = None
_env_cache: tuple[str | None, ChaosPlan | None] = (None, None)


def active() -> ChaosPlan | None:
    """The currently active plan: an :func:`inject` context if one is
    installed, else a (cached) plan parsed from ``GRAFT_CHAOS``."""
    global _env_cache
    with _lock:
        if _installed is not None:
            return _installed
        spec = os.environ.get("GRAFT_CHAOS") or None
        if spec != _env_cache[0]:
            plan = ChaosPlan(parse_plan(spec)) if spec else None
            _env_cache = (spec, plan)
        return _env_cache[1]


def on_call(site: str) -> None:
    """Hook for the executor: count this guarded call and maybe inject."""
    plan = active()
    if plan is not None:
        plan.on_call(site)


class inject:
    """Context manager installing a chaos plan for the enclosed block,
    overriding any GRAFT_CHAOS env plan.  Returns the plan so tests can
    read call counters afterwards."""

    def __init__(self, spec: str):
        self.plan = ChaosPlan(parse_plan(spec))
        self._prev: ChaosPlan | None = None

    def __enter__(self) -> ChaosPlan:
        global _installed
        with _lock:
            self._prev = _installed
            _installed = self.plan
        return self.plan

    def __exit__(self, *exc: object) -> None:
        global _installed
        with _lock:
            _installed = self._prev
