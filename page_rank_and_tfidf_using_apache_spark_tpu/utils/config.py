"""Config system: frozen dataclasses ↔ CLI flags.

Reference counterpart: ``SparkConf`` + positional ``spark-submit`` argv
(SURVEY.md §2.2 R10, §5.6).  Every semantic ambiguity in the reconstructed
reference behavior (dangling-mass handling, rank init, IDF smoothing — see
SURVEY.md §3.1/§4) is an explicit flag here, with the Spark-parity value as
the default.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import json
import os
import pathlib
import sys
import tempfile
import time
from typing import Any


# Every GRAFT_* environment knob the package reads, declared in one place.
# graftlint's ``env-knob-drift`` rule fails on any ``os.environ`` /
# ``os.getenv`` read of a ``GRAFT_*`` name that is not listed here, so a new
# knob cannot ship undocumented (add it here AND to the README env-knob
# table).  The set is parsed lexically by the linter — keep it a literal.
GRAFT_ENV_KNOBS: frozenset = frozenset(
    {
        "GRAFT_CHAOS",  # fault-injection plan (resilience/chaos.py)
        "GRAFT_ELASTIC",  # elastic mesh degradation on device loss
        # (resilience/elastic.py; "0" disables the mesh-shrink rung)
        "GRAFT_RETRY_MAX",  # max retries per guarded call
        "GRAFT_SYNC_DEADLINE_S",  # watchdog deadline for host syncs
        "GRAFT_STEP_DEADLINE_S",  # watchdog deadline for segment dispatch
        "GRAFT_BACKOFF_BASE_S",  # first backoff delay
        "GRAFT_BACKOFF_MAX_S",  # backoff ceiling
        "GRAFT_CKPT_KEEP",  # checkpoint retention count
        "GRAFT_SEMANTIC_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # semantic lint tier (read in bash, declared here all the same)
        "GRAFT_COST_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # tier-3 cost-model lint (read in bash; default 10s) — was read
        # undeclared until the tier-4 sweep caught the drift
        "GRAFT_CONC_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # tier-4 concurrency lint (read in bash; default 10s)
        "GRAFT_PERSIST_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # tier-5 persistence/crash-consistency lint (read in bash;
        # default 10s)
        "GRAFT_PROTO_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # tier-6 wire-protocol lint AND the protocol-harness conformance
        # smoke it derives (read in bash; default 10s)
        "GRAFT_TRACE_DIFF_THRESHOLD",  # tools/ci.sh per-phase wall-time
        # regression threshold for the trace-diff gate over the two newest
        # committed BENCH rounds (read in bash; default 0.35)
        "GRAFT_LOG_LEVEL",  # stderr log level (utils/metrics.py; default INFO)
        "GRAFT_TRACE_DIR",  # obs/ run-telemetry output dir: traced runs write
        # <name>.<pid>.trace.jsonl + .manifest.json here (unset = no trace)
        "GRAFT_TRACE_PARENT",  # cross-process trace id (obs/runtime.py): a
        # parent process (bench.py) exports one id; every child run adopts
        # it in its run_start event + manifest, so trace_report --stitch
        # reassembles one trace tree for the whole round
        "GRAFT_METRICS_PORT",  # live-metrics HTTP endpoint (obs/export.py):
        # unset = no exporter, 0 = ephemeral port, else the literal port;
        # serves /snapshot.json (rolling-window SLO snapshot) + /metrics
        # (Prometheus text) from a running server/soak
        "GRAFT_SOAK_DURATION_S",  # bench.py --soak: wall-clock length of
        # the production-soak scenario (serving/soak.py; default 60)
        "GRAFT_SOAK_QPS",  # bench.py --soak: closed-loop client target
        # request rate across all client threads (default 30)
        "GRAFT_SOAK_SLO_P99_MS",  # soak SLO target: served p99 latency
        # bound the latency error budget is scored against (default 500)
        "GRAFT_SOAK_SLO_AVAILABILITY",  # soak SLO target: good-request
        # fraction the availability error budget is scored against
        # (default 0.999)
        "GRAFT_SEG_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # segment-smoke gate (ingest → seal → query-from-new-segment →
        # merge under chaos; read in bash; default 15s)
        "GRAFT_OWNED_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # owned-strategy smoke (Zipf tolerance fixpoint on a 4-device
        # mesh under *:fail@%5 chaos, single-chip parity asserted; read
        # in bash; default 30s)
        "GRAFT_TUNE_BUDGET_S",  # tools/autotune.py wall-clock budget for
        # the measured sweep over cost-model survivors (also the ci.sh
        # autotune-smoke budget; default 60s — the pruned grid must fit)
        "GRAFT_TUNED_PROFILE",  # path to a tuned_profile_<backend>.json
        # the knob resolution ladder loads instead of the committed
        # per-backend default ("off" or empty disables profile loading
        # entirely: every knob falls back to TUNABLE_DEFAULTS)
        "GRAFT_FABRIC_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # fabric smoke (N=2 replica fleet, one SIGKILL mid-traffic,
        # recovery asserted with dropped=0; read in bash; default 25s)
        "GRAFT_FABRIC_REPLICAS",  # serving/fabric.py: replica-fleet size
        # the fleet soak / FabricConfig.from_env defaults to (default 2)
        "GRAFT_FED_SCRAPE_S",  # obs/federation.py: seconds between fleet
        # metrics scrapes of each replica's /snapshot.json (default 1.0;
        # a replica unanswered for 3 periods is labeled stale)
        "GRAFT_FED_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # federation+autoscale smoke (scrape → merged snapshot parses →
        # one forced scale-up decision; read in bash; default 25s)
        "GRAFT_AUTOSCALE_MIN",  # serving/fabric.py AutoscaleConfig: the
        # autoscaler's replica-count floor (default 1)
        "GRAFT_AUTOSCALE_MAX",  # serving/fabric.py AutoscaleConfig: the
        # autoscaler's replica-count ceiling (default 4)
        "GRAFT_AUTOSCALE_COOLDOWN_S",  # serving/fabric.py AutoscaleConfig:
        # minimum seconds between scale actions (default 10; the flap
        # gate in tools/trace_diff.py leans on this)
        "GRAFT_CACHE_PEEK_DEADLINE_S",  # serving/fabric.py: hard bound on
        # one owner cache-peek round-trip (default 0.25s) — the most a
        # slow/partitioned peer can ever add to a request's latency
        "GRAFT_CACHE_BREAKER_TRIP",  # serving/fabric.py: consecutive peer
        # timeouts before that peer's circuit breaker opens (default 3)
        "GRAFT_CACHE_BREAKER_PROBE_S",  # serving/fabric.py: seconds an
        # open breaker waits before letting one half-open probe through
        # (default 2.0)
        "GRAFT_DRAIN_BUDGET_S",  # tools/ci.sh wall-clock budget for the
        # drain-handoff kill-matrix smoke (SIGKILL pre-drain / mid-drain /
        # post-successor-healthy; read in bash; default 40s)
    }
)


# Single source of truth for every hand-picked performance-knob default.
# The dataclass fields below, the call-site signature defaults in
# ops//parallel//serving//dataflow/, and the ``TUNED_KNOBS`` search-space
# contract (analysis/registry.py) all read THIS table — the default-drift
# hazard ISSUE 16 closes was the same constant spelled independently at
# each of those sites.  graftlint tier 3's ``untuned-knob-read`` fails on
# any bare-literal default for one of these names in models//parallel//
# serving//dataflow/, and ``profile-drift`` cross-checks the table against
# the committed tuned profiles.  Parsed lexically by the linter — keep it
# a literal (plain int/float values, no expressions).
TUNABLE_DEFAULTS: dict = {
    # hybrid SpMV dense-head layout (ops/pagerank.py, PageRankConfig)
    "head_coverage": 0.5,
    "head_row_width": 128,
    # sort_shuffle bucket padding (ops/pagerank.py build_shuffle_layout)
    "shuffle_bucket_width": 8,
    # strategy="owned" replicated hub-head cap (parallel/pagerank_sharded.py)
    "owned_max_head": 4096,
    # staged ingest pipeline depths (dataflow/ingest.py, IngestConfig)
    "prefetch": 2,
    "pipeline_depth": 2,
    # streaming chunk re-packing target (models/tfidf.py; 0 = as-is)
    "pack_target_tokens": 0,
    # serving batch cap (serving/server.py ServeConfig, serving/soak.py)
    "max_batch": 8,
    # impacted-list scoring bucket layout (serving/server.py)
    "impact_bucket_width": 8,
    "impact_warm_buckets": 8192,  # 1 << 13
}


# The degradation rungs a guarded path may take past retry, declared in one
# place like the env knobs above.  graftlint's ``ladder-rung-drift`` rule
# fails on any ``obs.emit("degraded", ladder=<literal>)`` whose rung is not
# listed here, and on any declared rung that no resilience/ module
# implements — the ladder the README documents and the ladder the code
# walks cannot drift apart.  Parsed lexically by the linter — keep it a
# literal.  Full escalation order (README "Failure model and recovery"):
# retry -> mesh_shrink -> single_device -> cpu -> exhausted; retry and
# exhausted publish their own event kinds, so only the degradation rungs
# between them are ladder names.
DEGRADE_LADDER: tuple = (
    "mesh_shrink",  # rebuild the mesh over surviving devices (pow2 shrink)
    "single_device",  # the 1-device end of the shrink chain
    "cpu",  # re-lower on the CPU backend (single-chip paths)
    "respawn",  # replace a dead replica PROCESS (resilience/process.py):
    # past every in-process rung — recovery belongs to the supervisor
)


# Every long-lived thread the package spawns, declared in one place like
# the env knobs and ladder rungs above — the Spark counterpart is process
# isolation (executors, driver, block manager are separate JVMs); this
# one-process runtime gets a declared thread inventory instead.  Each entry
# is ``(name, owning module, locks it may hold)``:
#
# - ``name`` matches the literal ``threading.Thread(name=...)`` spelling;
#   a trailing ``*`` globs a formatted suffix (``soak-client-{i}``).
# - ``module`` is the repo-relative file that constructs the thread.
# - the lock tuple lists every lock the thread's target (plus same-file
#   callees) may acquire, spelled ``Class.attr`` / ``name`` (scoped to the
#   owning module) or fully qualified ``<module>::<Class>.<attr>``.
#
# graftlint validates both directions: tier 1's ``thread-registry-drift``
# fails on any Thread constructed with an undeclared (or statically
# unresolvable) name and on declared entries no code implements, and the
# tier-4 concurrency analyzer (``thread-lock-drift``) fails when a declared
# thread's target acquires a lock outside its declared set — so a new
# thread (or a new lock on an old thread) cannot land undocumented.
# Parsed lexically by the linter — keep it a literal.
THREAD_REGISTRY: tuple = (
    ("ingest-source",
     "page_rank_and_tfidf_using_apache_spark_tpu/dataflow/ingest.py",
     ()),  # Prefetched tokenize producer: lock-free bounded queue handoff
    ("ingest-h2d",
     "page_rank_and_tfidf_using_apache_spark_tpu/dataflow/ingest.py",
     ()),  # Prefetched H2D staging producer: same queue discipline
    ("resilience-*",
     "page_rank_and_tfidf_using_apache_spark_tpu/resilience/executor.py",
     ()),  # per-site watchdog attempt threads: run the guarded fn only
    ("graft-metrics-http",
     "page_rank_and_tfidf_using_apache_spark_tpu/obs/export.py",
     # handler threads read through the hub's own instrument locks
     ("page_rank_and_tfidf_using_apache_spark_tpu/obs/metrics.py::"
      "MetricsHub._lock",)),
    ("tfidf-serve-drain",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/server.py",
     # cache + stats; NEVER _submit_lock (the drain must keep consuming
     # while a submitter blocks on the bounded queue holding it)
     ("TfidfServer._lock",)),
    ("segment-merge",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/segments.py",
     # background compaction: its own stats lock + the module commit lock
     # serializing manifest read-modify-write against ingest seals
     ("SegmentMerger._lock", "_COMMIT_LOCK")),
    ("soak-ingest",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/soak.py",
     ("_Soak._lock",
      # delta-segment commits go through the segments module commit lock
      "page_rank_and_tfidf_using_apache_spark_tpu/serving/segments.py::"
      "_COMMIT_LOCK")),
    ("soak-prior",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/soak.py",
     ("_Soak._lock",)),
    ("soak-client-*",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/soak.py",
     ("_Soak._lock",)),
    ("fleet-ingest",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/soak.py",
     ("_FleetSoak._lock",
      # delta-segment commits go through the segments module commit lock
      "page_rank_and_tfidf_using_apache_spark_tpu/serving/segments.py::"
      "_COMMIT_LOCK")),
    ("fleet-client-*",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/soak.py",
     ("_FleetSoak._lock",
      # fabric.query folds delivery stats under the router's own lock
      "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py::"
      "ServingFabric._lock")),
    ("proc-stdout-drain",
     "page_rank_and_tfidf_using_apache_spark_tpu/resilience/process.py",
     ()),  # drains a supervised child's stdout so it can't fill the pipe
    ("fabric-replica-poll",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     # floor/generation state under the replica's own lock; the hot swap
     # itself goes through the server's refresh path
     ("_Replica._lock",)),
    ("fabric-health",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     ("ServingFabric._lock",)),  # suspect set + per-replica stats fold
    ("fabric-supervisor",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     ("ServingFabric._lock",)),  # handle/port swap on respawn
    ("fed-scraper",
     "page_rank_and_tfidf_using_apache_spark_tpu/obs/federation.py",
     # per-replica mergeable/staleness state under the fleet hub's lock;
     # the guarded fetch itself runs on a resilience-* watchdog thread
     ("FleetHub._lock",)),
    ("fabric-autoscaler",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     # scale_up/scale_down swap membership + ring under the router's lock
     ("ServingFabric._lock",)),
    ("fabric-peer-peek",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     ()),  # disposable bounded-deadline cache peek: one HTTP round-trip
    # into a result cell, abandoned past the deadline (ISSUE 20)
    ("fabric-peer-fill",
     "page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py",
     # owner write-back drain: breaker + peer tallies under the replica's
     # peer lock, never the serving hot path's _lock
     ("_Replica._peer_lock",)),
    ("bench-roll-load",
     "bench.py",
     # closed-loop load during the bench child's rolling-restart probe;
     # fabric.query folds delivery stats under the router's own lock
     ("page_rank_and_tfidf_using_apache_spark_tpu/serving/fabric.py::"
      "ServingFabric._lock",)),
)


def ensure_dtype_support(dtype: str) -> None:
    """Enable jax's x64 mode when a 64-bit compute dtype is requested.

    Without this, ``dtype="float64"`` silently degrades to float32 (jax's
    default), which surfaces as reduction-order noise ~1e-5 between shard
    strategies instead of the documented ≤1e-9 chip-count invariance.
    Called by every run_* driver; idempotent."""
    import numpy as np

    if np.dtype(dtype).itemsize == 8:
        import jax

        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Knobs of the staged ingest pipeline (``dataflow.ingest.chunked_ingest``):
    tokenize → H2D staging → compute run as overlapped stages, and these two
    depths bound how far each stage may run ahead (the backpressure that
    keeps host and device memory flat).

    - ``prefetch``: how many tokenized chunks the background tokenizer
      thread may buffer ahead of the H2D stage, AND how many launched
      device chunks stay in flight before the host drains the oldest.
      0 = no tokenizer thread, every chunk drains before the next launches.
    - ``pipeline_depth``: how many H2D-staged chunks (``jax.device_put``
      issued on the transfer thread, compute not yet dispatched) may be
      held in device memory.  0 = staging runs inline on the calling
      thread (no transfer thread); the default 2 double-buffers chunk
      N+1's transfer under chunk N's compute.

    Results are bit-identical at every depth — only scheduling changes.
    """

    prefetch: int = TUNABLE_DEFAULTS["prefetch"]
    pipeline_depth: int = TUNABLE_DEFAULTS["pipeline_depth"]

    def __post_init__(self) -> None:
        if self.prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {self.prefetch}")
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0, got {self.pipeline_depth}"
            )


class DanglingMode(str, enum.Enum):
    """What happens to rank mass at nodes with no out-links.

    The canonical Spark example silently drops it (dangling nodes never
    appear as a ``links`` key, so their mass vanishes each iteration —
    SURVEY.md §3.1).  ``REDISTRIBUTE`` is the textbook/networkx behavior:
    dangling mass is spread uniformly over all nodes, keeping ``sum(ranks)``
    constant.
    """

    DROP = "drop"
    REDISTRIBUTE = "redistribute"


class RankInit(str, enum.Enum):
    """Initial rank value. The canonical Spark example uses 1.0 per node
    (so ranks sum to N); ``UNIFORM`` is 1/N (ranks sum to 1)."""

    ONE = "one"
    UNIFORM = "uniform"


class IdfMode(str, enum.Enum):
    """IDF formula variant (SURVEY.md §4: the reference's exact smoothing is
    unverifiable, so all common variants are pinned behind this flag).

    - CLASSIC: ``log(N / df)`` — the textbook formula most course projects use.
    - MLLIB:   ``log((N + 1) / (df + 1))`` — Spark MLlib's smoothing.
    - SMOOTH:  ``log((1 + N) / (1 + df)) + 1`` — sklearn's ``smooth_idf``.
    """

    CLASSIC = "classic"
    MLLIB = "mllib"
    SMOOTH = "smooth"


class TfMode(str, enum.Enum):
    """TF variant: RAW counts (Spark canonical), FREQ = count/doc_len,
    LOGNORM = 1 + log(count)."""

    RAW = "raw"
    FREQ = "freq"
    LOGNORM = "lognorm"


@dataclasses.dataclass(frozen=True)
class PageRankConfig:
    """Configuration for a PageRank run.

    Mirrors the reference CLI shape ``pagerank <edges> <iters>`` plus
    explicit flags for every reconstructed-semantics choice.
    """

    iterations: int = 20
    damping: float = 0.85
    # Convergence: if tol > 0, stop early when the L1 delta between
    # successive rank vectors falls below tol (runs inside lax.while_loop).
    tol: float = 0.0
    dangling: DanglingMode = DanglingMode.DROP
    init: RankInit = RankInit.ONE
    # Exact emulation of the canonical Spark example's shrinking key-set
    # semantics (nodes absent from the join drop out — SURVEY.md §3.1).
    # Only meaningful with dangling=DROP, init=ONE.
    spark_exact: bool = False
    # Personalized PageRank: restart concentrated on these node ids instead
    # of uniform (BASELINE.json:10). None => standard PageRank.
    personalize: tuple[int, ...] | None = None
    # Sparse matvec implementation: "segment" (sorted segment_sum — default),
    # "bcoo" (jax.experimental.sparse), "cumsum"/"cumsum_mxu" (scatter-free
    # prefix-sum diff), "hybrid" (degree-aware dense MXU head + segment
    # tail), "sort_shuffle" (fixed-width dst buckets, pure reshape→reduce),
    # or "pallas" (hand-written TPU prefix-sum kernel).
    spmv_impl: str = "segment"
    # spmv_impl="hybrid" layout knobs: the head is the smallest top-k
    # in-degree set covering ~head_coverage of all edges (every member's
    # in-degree >= the dense row width, which adapts down from
    # head_row_width on small graphs).
    head_coverage: float = TUNABLE_DEFAULTS["head_coverage"]
    head_row_width: int = TUNABLE_DEFAULTS["head_row_width"]
    # spmv_impl="sort_shuffle": bucket width each destination's edge run is
    # padded to (the factor the dynamic reduction shrinks by).
    shuffle_bucket_width: int = TUNABLE_DEFAULTS["shuffle_bucket_width"]
    # Sharded strategy="owned" (ISSUE 15): cap on the replicated hub-head
    # size — the head mini-state and its per-step psum are O(head), so
    # this bounds both; head_coverage doubles as the endpoint-coverage
    # target of the combined-degree head policy (ops.boundary).
    owned_max_head: int = TUNABLE_DEFAULTS["owned_max_head"]
    dtype: str = "float32"
    # Checkpoint every k iterations (0 = off) into checkpoint_dir.
    checkpoint_every: int = 0
    checkpoint_dir: str | None = None

    def __post_init__(self) -> None:
        if self.iterations < 0:
            raise ValueError(f"iterations must be >= 0, got {self.iterations}")
        if not 0.0 <= self.damping <= 1.0:
            raise ValueError(f"damping must be in [0, 1], got {self.damping}")
        # Accept plain strings for enum fields (CLI / JSON round-trips) —
        # coerce BEFORE any enum-identity validation below.
        object.__setattr__(self, "dangling", DanglingMode(self.dangling))
        object.__setattr__(self, "init", RankInit(self.init))
        if self.spark_exact and self.dangling is not DanglingMode.DROP:
            raise ValueError("spark_exact requires dangling=drop")
        if self.spark_exact and self.personalize is not None:
            # the canonical Spark example has no restart vector; silently
            # ignoring --personalize would be worse than refusing
            raise ValueError("spark_exact cannot be personalized")
        if self.spmv_impl not in ("segment", "bcoo", "cumsum", "cumsum_mxu",
                                  "hybrid", "sort_shuffle", "pallas"):
            raise ValueError(f"unknown spmv_impl {self.spmv_impl!r}")
        if not 0.0 < self.head_coverage <= 1.0:
            raise ValueError(
                f"head_coverage must be in (0, 1], got {self.head_coverage}"
            )
        if self.head_row_width < 8 or self.shuffle_bucket_width < 2:
            raise ValueError(
                "head_row_width must be >= 8 and shuffle_bucket_width >= 2, "
                f"got {self.head_row_width}/{self.shuffle_bucket_width}"
            )
        if self.owned_max_head < 0:
            raise ValueError(
                f"owned_max_head must be >= 0, got {self.owned_max_head}"
            )
        if self.spark_exact and self.spmv_impl not in ("segment", "bcoo"):
            # spark_exact's presence test counts unit contributions through
            # the SpMV; a float32 prefix sum stops resolving +1.0 past 2^24
            # accumulated mass, silently zeroing live nodes at large-graph
            # scale.  spark_exact is a parity mode — keep it on exact impls.
            raise ValueError("spark_exact requires spmv_impl='segment' or 'bcoo'")
        if self.personalize is not None:
            object.__setattr__(self, "personalize", tuple(int(x) for x in self.personalize))

    def config_hash(self) -> str:
        """Hash of the *semantic* fields only: run length, tolerance, and
        checkpoint placement are operational — a checkpoint taken at
        iteration k is valid for any longer run of the same semantics."""
        return _hash_config(self, exclude={"iterations", "tol", "checkpoint_every", "checkpoint_dir"})


@dataclasses.dataclass(frozen=True)
class TfidfConfig:
    """Configuration for a TF-IDF run over a corpus.

    ``vocab_bits`` fixes the hashed vocabulary to ``2**vocab_bits`` ids
    (BASELINE.json:8 names 2^18 for the 20-Newsgroups config).
    """

    vocab_bits: int = 18
    ngram: int = 1  # 1 = unigram, 2 = uni+bigram (BASELINE.json:11)
    tf_mode: TfMode = TfMode.RAW
    idf_mode: IdfMode = IdfMode.CLASSIC
    l2_normalize: bool = False
    lowercase: bool = True
    min_token_len: int = 1
    # Streaming ingest (BASELINE.json:11): docs are fed in fixed-size chunks
    # of this many tokens; 0 = single batch.
    chunk_tokens: int = 0
    # Staged ingest pipeline (SURVEY.md §5.7, IngestConfig above): how many
    # tokenized chunks the background tokenizer thread may run ahead of the
    # H2D stage / how many launched device chunks stay in flight before the
    # host syncs (prefetch), and how many H2D-staged chunks the transfer
    # thread may hold in device memory (pipeline_depth).  0/0 = fully
    # serial (tokenize → put → compute → pull, one chunk at a time).
    prefetch: int = TUNABLE_DEFAULTS["prefetch"]
    pipeline_depth: int = TUNABLE_DEFAULTS["pipeline_depth"]
    # Re-pack incoming document chunks so each carries ~this many tokens
    # before padding (dataflow.ingest.pack_doc_chunks): the chunk kernel
    # sorts/reduces the PADDED arrays, so half-full chunks pay ~2x the
    # batch pipeline's compute — most of the measured streaming-vs-batch
    # gap (BENCH_r07).  0 = take the caller's chunking as-is.  Documents
    # never split, so results are identical either way; checkpoint chunk
    # indices count PACKED chunks (resume with the same target).
    pack_target_tokens: int = TUNABLE_DEFAULTS["pack_target_tokens"]
    checkpoint_every: int = 0  # chunks between checkpoints (0 = off)
    checkpoint_dir: str | None = None
    dtype: str = "float32"

    def __post_init__(self) -> None:
        if not 1 <= self.vocab_bits <= 30:
            raise ValueError(f"vocab_bits must be in [1, 30], got {self.vocab_bits}")
        if self.ngram not in (1, 2):
            raise ValueError(f"ngram must be 1 or 2, got {self.ngram}")
        if self.prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {self.prefetch}")
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth must be >= 0, got {self.pipeline_depth}"
            )
        if self.pack_target_tokens < 0:
            raise ValueError(
                f"pack_target_tokens must be >= 0, got {self.pack_target_tokens}"
            )
        object.__setattr__(self, "tf_mode", TfMode(self.tf_mode))
        object.__setattr__(self, "idf_mode", IdfMode(self.idf_mode))

    @property
    def vocab_size(self) -> int:
        return 1 << self.vocab_bits

    def ingest(self) -> IngestConfig:
        """The staged-pipeline knobs as the dataflow core's IngestConfig."""
        return IngestConfig(prefetch=self.prefetch,
                            pipeline_depth=self.pipeline_depth)

    def config_hash(self) -> str:
        """Semantic fields only (chunking/checkpoint placement excluded —
        the accumulated DF/TF state is chunk-boundary-independent)."""
        return _hash_config(
            self,
            exclude={"chunk_tokens", "prefetch", "pipeline_depth",
                     "pack_target_tokens", "checkpoint_every",
                     "checkpoint_dir"},
        )


@dataclasses.dataclass(frozen=True)
class HitsConfig:
    """Configuration for a HITS (hubs/authorities) run — a second SpMV
    fixpoint workload over the same graph substrate (dataflow/hits.py).
    Field names mirror PageRankConfig so the shared segment driver
    (dataflow.fixpoint.run_segments) drives it unchanged; iteration
    semantics mirror networkx.hits (per-step max-normalization, L1
    convergence on the hub vector, final sum-normalization)."""

    iterations: int = 100
    tol: float = 1e-8
    dtype: str = "float32"
    checkpoint_every: int = 0
    checkpoint_dir: str | None = None

    def __post_init__(self) -> None:
        if self.iterations < 0:
            raise ValueError(f"iterations must be >= 0, got {self.iterations}")
        if self.tol < 0:
            raise ValueError(f"tol must be >= 0, got {self.tol}")

    def config_hash(self) -> str:
        return _hash_config(
            self, exclude={"iterations", "tol", "checkpoint_every", "checkpoint_dir"}
        )


@dataclasses.dataclass(frozen=True)
class ComponentsConfig:
    """Configuration for connected components via min-label propagation
    (dataflow/components.py): the PageRank SpMV skeleton with a ``min``
    combine, iterated until no label changes.  ``iterations`` caps the
    label-propagation rounds (>= the undirected diameter for an exact
    answer; the run stops early the step nothing changes)."""

    iterations: int = 200
    checkpoint_every: int = 0
    checkpoint_dir: str | None = None
    # Fixed convergence gauge: delta is the COUNT of labels that changed,
    # so any tol in (0, 1) means "stop when nothing changed".  Declared a
    # field (not a property) so dataclasses.replace in the segment driver
    # round-trips it.
    tol: float = 0.5

    def __post_init__(self) -> None:
        if self.iterations < 0:
            raise ValueError(f"iterations must be >= 0, got {self.iterations}")

    def config_hash(self) -> str:
        return _hash_config(
            self, exclude={"iterations", "tol", "checkpoint_every", "checkpoint_dir"}
        )


@dataclasses.dataclass(frozen=True)
class Bm25Config:
    """Okapi BM25 weighting knobs (dataflow/bm25.py) — the second ranker
    over the SAME postings COO the TF-IDF pipeline materializes.  The
    Lucene idf variant ``log(1 + (N - df + 0.5)/(df + 0.5))`` keeps
    weights non-negative."""

    k1: float = 1.5
    b: float = 0.75

    def __post_init__(self) -> None:
        if self.k1 < 0:
            raise ValueError(f"k1 must be >= 0, got {self.k1}")
        if not 0.0 <= self.b <= 1.0:
            raise ValueError(f"b must be in [0, 1], got {self.b}")

    def config_hash(self) -> str:
        return _hash_config(self)


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    return obj


def config_to_json(cfg: Any) -> str:
    return json.dumps(_to_jsonable(cfg), sort_keys=True)


def _hash_config(cfg: Any, exclude: set[str] = frozenset()) -> str:
    """Stable short hash used to tag checkpoints and metrics as belonging to
    one semantic configuration (SURVEY.md §5.4)."""
    d = {k: v for k, v in _to_jsonable(cfg).items() if k not in exclude}
    return hashlib.sha256(json.dumps(d, sort_keys=True).encode()).hexdigest()[:16]


# --------------------------------------------------------------------------
# Tuned-profile artifact (ISSUE 16): the committed per-backend knob optimum
# tools/autotune.py measures.  Spark counterpart: a tuned ``spark.conf``
# shipped alongside the job — platform-specific values for the same named
# knobs the code reads through one resolution ladder.
# --------------------------------------------------------------------------

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


class TunedProfileError(ValueError):
    """A tuned-profile artifact failed structural validation."""


@dataclasses.dataclass(frozen=True)
class TunedProfile:
    """One backend's measured knob optimum, as loaded from a
    ``tuned_profile_<backend>.json`` artifact.

    ``knobs`` maps TUNABLE_DEFAULTS names to the measured-best values;
    ``measured`` carries the sweep evidence (bench keys and the speedup vs
    defaults) for forensics.  ``source`` records which rung of the
    resolution ladder produced this profile ("explicit" path argument,
    "env" GRAFT_TUNED_PROFILE, or the "committed" per-backend default) —
    run manifests persist it so a round's numbers are attributable."""

    backend: str
    knobs: dict
    path: str | None = None
    git_sha: str | None = None
    created_wall: float | None = None
    measured: dict | None = None
    source: str = "explicit"

    def knob(self, name: str, default: Any = None) -> Any:
        return self.knobs.get(name, default)


def default_backend() -> str:
    """Best stdlib-only guess at the backend this process computes on:
    a live jax module wins, then JAX_PLATFORMS, then "cpu".  Deliberately
    never IMPORTS jax — the bench parent and the lint tiers resolve
    profiles without bringing a runtime up."""
    mod = sys.modules.get("jax")
    if mod is not None:
        try:
            return str(mod.default_backend())
        except Exception:  # pragma: no cover - partially initialised jax
            pass
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats:
        first = plats.split(",")[0].strip()
        if first:
            return first
    return "cpu"


def profile_path(backend: str, root: str | pathlib.Path | None = None) -> str:
    """Committed location of ``backend``'s tuned profile artifact."""
    base = pathlib.Path(root) if root is not None else _REPO_ROOT
    return str(base / f"tuned_profile_{backend}.json")


def load_tuned_profile(
    backend: str | None = None,
    path: str | pathlib.Path | None = None,
    *,
    root: str | pathlib.Path | None = None,
) -> TunedProfile | None:
    """Resolve and load the tuned profile for ``backend``.

    Resolution ladder (highest wins):

    1. an explicit ``path`` argument (CLI ``--tuned-profile``);
    2. the ``GRAFT_TUNED_PROFILE`` env knob — ``"off"``/empty disables
       profile loading entirely (returns None: every knob falls back to
       ``TUNABLE_DEFAULTS``);
    3. the committed ``tuned_profile_<backend>.json`` at the repo root
       (None when absent — a missing committed profile is not an error).

    A profile stamped for a DIFFERENT backend raises ``ProvenanceError``
    (same guard class as the measured cost artifacts): a CPU-tuned
    optimum must never silently steer a TPU run, nor vice versa.
    """
    from .artifacts import ProvenanceError

    if backend is None:
        backend = default_backend()
    source = "explicit"
    if path is None:
        env = os.environ.get("GRAFT_TUNED_PROFILE")
        if env is not None:
            if env.strip().lower() in ("", "off", "0", "none"):
                return None
            path, source = env, "env"
        else:
            path, source = profile_path(backend, root=root), "committed"
            if not os.path.exists(path):
                return None
    try:
        text = pathlib.Path(path).read_text()
    except OSError as exc:
        raise TunedProfileError(
            f"tuned profile {path} unreadable: {exc}"
        ) from exc
    try:
        record = json.loads(text)
    except json.JSONDecodeError as exc:
        raise TunedProfileError(
            f"tuned profile {path} is not valid JSON: {exc}"
        ) from exc
    if not isinstance(record, dict) or "backend" not in record \
            or "knobs" not in record:
        raise TunedProfileError(
            f"tuned profile {path} missing required keys "
            "('backend', 'knobs')"
        )
    stamped = str(record["backend"])
    if stamped != backend:
        raise ProvenanceError(
            f"tuned profile {path} was measured on backend {stamped!r} but "
            f"this run computes on {backend!r}; refusing to load a "
            "cross-backend optimum (re-tune with tools/autotune.py on this "
            "backend, or point GRAFT_TUNED_PROFILE at the right artifact)"
        )
    knobs = record["knobs"]
    if not isinstance(knobs, dict) or not all(
        isinstance(v, (int, float)) and not isinstance(v, bool)
        for v in knobs.values()
    ):
        raise TunedProfileError(
            f"tuned profile {path} knobs must map names to numbers"
        )
    return TunedProfile(
        backend=stamped,
        knobs=dict(knobs),
        path=str(path),
        git_sha=record.get("git_sha"),
        created_wall=record.get("created_wall"),
        measured=record.get("measured"),
        source=source,
    )


def write_tuned_profile(
    path: str | pathlib.Path,
    backend: str,
    knobs: dict,
    *,
    measured: dict | None = None,
    force: bool = False,
) -> dict:
    """Commit a tuned profile artifact durably.

    Same write discipline as the cost artifacts: backend-stamped,
    ``check_overwrite`` guarded (a non-TPU run may not clobber a
    TPU-stamped profile without ``force``), staged to a temp file and
    published with ``durable_replace`` so a crash at any point leaves
    either the old profile or the new one — never a torn JSON."""
    from .artifacts import check_overwrite
    from .checkpoint import durable_replace

    check_overwrite(path, backend, force=force)
    record = {
        "backend": backend,
        "knobs": {str(k): knobs[k] for k in sorted(knobs)},
        "git_sha": _git_short_sha(),
        "created_wall": time.time(),
        "measured": dict(measured or {}),
    }
    target = pathlib.Path(path)
    fd, tmp = tempfile.mkstemp(dir=str(target.parent) or ".",
                               suffix=".tuned.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
        durable_replace(tmp, str(target))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return record


def tuned_config(cls: type, profile: TunedProfile | None = None,
                 **overrides: Any) -> Any:
    """Build config dataclass ``cls`` through the knob resolution ladder:
    explicit non-None override > tuned-profile knob > field default (which
    reads ``TUNABLE_DEFAULTS``).  ``None`` overrides mean "not specified"
    — exactly what argparse hands over for an unset flag — so CLI layers
    pass their whole namespace through without pre-filtering."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(overrides) - set(fields)
    if unknown:
        raise TypeError(
            f"{cls.__name__} has no fields {sorted(unknown)}"
        )
    kwargs: dict = {}
    for name, field in fields.items():
        if overrides.get(name) is not None:
            kwargs[name] = overrides[name]
        elif profile is not None and name in TUNABLE_DEFAULTS \
                and name in profile.knobs:
            value = profile.knobs[name]
            # int knobs arrive as JSON numbers; preserve the field's kind
            if isinstance(TUNABLE_DEFAULTS.get(name), int):
                value = int(value)
            kwargs[name] = value
    return cls(**kwargs)


def _git_short_sha() -> str | None:
    """Short HEAD sha of the repo the profile was tuned in (None when git
    is unavailable — e.g. a deployed artifact tree)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=str(_REPO_ROOT), capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None
