"""Tracing / profiling (SURVEY.md §5.1).

Reference counterpart: the Spark web UI + event log.  Here the equivalent is
an XLA device trace: ``trace(logdir)`` wraps a region in
``jax.profiler.trace`` producing a TensorBoard-compatible profile of every
compiled program and collective, and ``annotate(name)`` marks host-side
phases so ingest vs compute shows up in the timeline.
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def trace(logdir: str | None) -> Iterator[None]:
    """Profile the enclosed region into ``logdir`` (no-op if None)."""
    if logdir is None:
        yield
        return
    import jax.profiler

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named host-side phase, visible in the profiler timeline."""
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield
