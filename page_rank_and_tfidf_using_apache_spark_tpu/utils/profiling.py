"""Tracing / profiling (SURVEY.md §5.1).

Reference counterpart: the Spark web UI + event log.  Two layers here:

- ``trace(logdir)`` wraps a region in ``jax.profiler.trace`` producing a
  TensorBoard-compatible profile of every compiled program and collective;
- ``annotate(name)`` marks a host-side phase.  Since ISSUE 4 this is an
  alias for :func:`obs.span`: the phase lands in the run's crash-safe
  JSONL trace (with nesting, thread identity and wall time) *and* — when
  jax is imported — in the XLA profiler timeline via
  ``jax.profiler.TraceAnnotation``, so host phases line up with device
  timelines in one view.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

from page_rank_and_tfidf_using_apache_spark_tpu import obs


@contextlib.contextmanager
def trace(logdir: str | None) -> Iterator[None]:
    """Profile the enclosed region into ``logdir`` (no-op if None)."""
    if logdir is None:
        yield
        return
    import jax.profiler

    with jax.profiler.trace(logdir):
        yield


def annotate(name: str, **attrs):
    """Named host-side phase: an obs span (JSONL trace + nesting) bridged
    to the jax profiler timeline when jax is loaded."""
    return obs.span(name, **attrs)
