"""Structured metrics / logging (SURVEY.md §5.5).

Reference counterpart: the Spark UI stage/task counters and log4j lines.
Here every iteration emits one structured record
(``iter, l1_delta, dangling_mass, secs``), collected in-memory and dumpable
as JSON for the bench harness that feeds BASELINE.md.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import sys
import time
from typing import Any, Iterator

logger = logging.getLogger("pr_tfidf_tpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)


@dataclasses.dataclass
class MetricsRecorder:
    """Collects per-step structured records and run-level scalars."""

    records: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    scalars: dict[str, Any] = dataclasses.field(default_factory=dict)

    def record(self, **kwargs: Any) -> None:
        self.records.append(kwargs)
        logger.info("%s", json.dumps(kwargs, default=float))

    def scalar(self, name: str, value: Any) -> None:
        self.scalars[name] = value

    def to_json(self) -> str:
        return json.dumps(
            {"records": self.records, "scalars": self.scalars}, default=float
        )

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


class Timer:
    """Wall-clock timer context; remember to block_until_ready() the device
    values inside the block — XLA dispatch is async."""

    def __enter__(self) -> "Timer":
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = time.perf_counter() - self.start


def timed() -> Iterator[Timer]:  # pragma: no cover - convenience alias
    return Timer()
