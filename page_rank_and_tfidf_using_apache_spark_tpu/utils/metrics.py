"""Structured metrics / logging (SURVEY.md §5.5).

Reference counterpart: the Spark UI stage/task counters and log4j lines.
Here every iteration emits one structured record
(``iter, l1_delta, dangling_mass, secs``), collected in-memory and dumpable
as JSON for the bench harness that feeds BASELINE.md.

Since ISSUE 4 the recorder is a *publisher* onto the obs event bus: every
``record(...)`` also lands on the process bus as a ``kind="metric"`` event,
so a traced run's JSONL file carries the full legacy record stream next to
the span/retry/checkpoint telemetry — and the recorder itself is
thread-safe (records arrive from the streaming tokenizer/prefetch threads
as well as the main loop; the ``unsynced-thread-state`` lint patrols
exactly this class of mutation).

The stderr log level follows the ``GRAFT_LOG_LEVEL`` env knob (default
INFO; declared in ``utils/config.GRAFT_ENV_KNOBS``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Iterator

from page_rank_and_tfidf_using_apache_spark_tpu import obs


def resolve_log_level(spec: str | None, default: int = logging.INFO) -> int:
    """Map a GRAFT_LOG_LEVEL string ('debug', 'WARNING', '30', ...) to a
    logging level int; unknown spellings fall back to ``default``."""
    if not spec:
        return default
    spec = spec.strip()
    if spec.isdigit():
        # "0" means "log everything": setLevel(NOTSET) would instead defer
        # to the root logger (WARNING), silencing the metric lines
        return int(spec) or logging.DEBUG
    level = logging.getLevelName(spec.upper())
    return level if isinstance(level, int) else default


logger = logging.getLogger("pr_tfidf_tpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(resolve_log_level(os.environ.get("GRAFT_LOG_LEVEL")))


@dataclasses.dataclass
class MetricsRecorder:
    """Collects per-step structured records and run-level scalars.

    Thread-safe: ``record``/``scalar`` may be called from worker threads
    (streaming prefetch, watchdog) concurrently with the main loop."""

    records: list[dict[str, Any]] = dataclasses.field(default_factory=list)
    scalars: dict[str, Any] = dataclasses.field(default_factory=dict)
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def record(self, **kwargs: Any) -> None:
        with self._lock:
            self.records.append(kwargs)
        obs.emit("metric", **kwargs)
        logger.info("%s", json.dumps(kwargs, default=float))

    def scalar(self, name: str, value: Any) -> None:
        with self._lock:
            self.scalars[name] = value

    def to_json(self) -> str:
        with self._lock:
            return json.dumps(
                {"records": list(self.records), "scalars": dict(self.scalars)},
                default=float,
            )

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())


def percentile(sorted_xs, p: float):
    """Nearest-rank percentile over an ascending sequence (None when
    empty) — the ONE convention every latency report uses (bench.py's
    served-QPS block, cli.serve's summary, and tools/trace_report.py's
    ``_pct``/``sync_p99`` implement the identical formula; trace_report
    stays stdlib-only so it carries its own copy), so the same run never
    reports two different p99s across artifacts."""
    if not sorted_xs:
        return None
    n = len(sorted_xs)
    rank = -(-int(p * 100) * n // 100)  # ceil without math
    return sorted_xs[min(n - 1, max(0, rank - 1))]


class Timer:
    """Wall-clock timer context; remember to block_until_ready() the device
    values inside the block — XLA dispatch is async."""

    def __enter__(self) -> "Timer":
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = time.perf_counter() - self.start


def timed() -> Iterator[Timer]:  # pragma: no cover - convenience alias
    return Timer()
