"""Checkpoint / resume (SURVEY.md §5.3/§5.4).

Reference counterpart: Spark lineage recomputation + ``RDD.checkpoint()``.
On TPU there is no lineage to replay, so recovery is restart-from-snapshot:
we save the live state arrays plus a step counter and the config hash, and
refuse to resume under a different semantic configuration.

Format: flat ``.npz`` (numpy) plus a JSON sidecar — deliberately dependency
-free and host-readable.  Writes are atomic (tmp file + rename) so a kill
mid-write never corrupts the latest checkpoint; the fault-injection test in
``tests/test_checkpoint.py`` exercises exactly that.

Mesh-shape tagging (ISSUE 5): sharded callers put ``devices=N`` in
``extra`` so a snapshot records which mesh wrote it, but the *payload* is
always logical global state (the [n] rank vector, the accumulated DF/TF
parts) — never per-device shards.  That is what makes checkpoints readable
across elastic mesh shrinks: a snapshot written by an 8-device run resumes
on 4, 1, or the CPU backend unchanged, and ``config_hash`` rightly ignores
topology because device count is operational, not semantic.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs


_META_KEY = "__ckpt_meta__"
_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.npz$")
_VDIR_RE = re.compile(r"^v(\d{4})$")


def _fsync_path(path: str) -> None:
    """fsync one existing file or directory by path (open, fsync, close)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(directory: str) -> None:
    """Flush a directory's entry table — the other half of a durable
    rename.  ``os.replace`` makes the swap atomic for concurrent READERS,
    but only an fsync of the parent directory makes the new entry itself
    survive a power loss; without it a crash can roll the directory back
    to a state where the pointer names a payload that never got linked."""
    _fsync_path(directory or ".")


def durable_replace(src: str, dst: str) -> None:
    """The blessed commit idiom for pointer-visible writes (graftlint
    tier 5, ``atomic-write-drift``): fsync the staged payload — a file, or
    a staged directory plus every file in it — atomically rename it into
    place, then fsync the destination's parent directory so the rename
    itself is durable.  Readers never see a torn payload (the rename is
    atomic) AND a crash after return can never lose state that a pointer
    flip — possibly this very call — has made reachable."""
    if os.path.isdir(src):
        # every file AND every directory entry table, bottom-up — a
        # nested member renamed into place un-fsynced would be exactly
        # the lost-payload class this helper exists to close
        for dirpath, _dirnames, filenames in os.walk(src, topdown=False):
            for name in sorted(filenames):
                _fsync_path(os.path.join(dirpath, name))
            _fsync_path(dirpath)
    else:
        _fsync_path(src)
    os.replace(src, dst)  # graftlint: disable=atomic-write-drift (this IS the blessed idiom's interior)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def _write_pointer(directory: str, name: str, pointer: str = "LATEST") -> None:
    """Durably flip the directory's pointer file to ``name`` — the same
    tmp-file hygiene as the checkpoint payload write (a failure between
    mkstemp and replace must not leak the tempfile), with the flip itself
    fsync'd: a pointer that names only fsync'd payloads but is not itself
    durable can still vanish on power loss, silently rolling back a
    commit the caller already reported."""
    ptr = os.path.join(directory, pointer)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(name)
        durable_replace(tmp, ptr)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_checkpoint(
    directory: str,
    step: int,
    arrays: dict[str, np.ndarray],
    config_hash: str,
    extra: dict[str, Any] | None = None,
    keep: int | None = None,
) -> str:
    """Atomically write ``step``'s state; returns the checkpoint path.

    ``keep`` bounds how many ``.npz`` snapshots stay on disk (oldest pruned
    after the LATEST pointer flips); None reads ``GRAFT_CKPT_KEEP``
    (default 8), and 0 keeps everything.
    """
    os.makedirs(directory, exist_ok=True)
    meta = {"step": int(step), "config_hash": config_hash, "extra": extra or {}}
    path = os.path.join(directory, f"ckpt_{step:08d}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(
                f,
                **{k: np.asarray(v) for k, v in arrays.items()},
                **{_META_KEY: np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)},
            )
        # fsync + atomic rename + parent-dir fsync: the LATEST flip below
        # makes this payload pointer-visible, so the write must be durable
        # BEFORE the pointer can name it (tier-5 atomic-write-drift)
        durable_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _write_pointer(directory, os.path.basename(path))
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        nbytes = None  # a concurrent gc may beat us to it — tolerated above
    obs.emit("checkpoint_save", path=path, step=int(step), bytes=nbytes)
    obs.counter("checkpoint_saves")
    if keep is None:
        keep = int(os.environ.get("GRAFT_CKPT_KEEP", 8))
    if keep > 0:
        gc_checkpoints(directory, keep=keep)
    return path


def gc_checkpoints(directory: str, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` snapshots (by step number).

    The file the LATEST pointer names is always kept, whatever its step —
    a resumable run must never have its pointer dangling.  Returns the
    deleted paths (for logging/tests).
    """
    if keep <= 0:
        raise ValueError(f"keep must be >= 1, got {keep}")
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    snaps = sorted(
        (int(m.group(1)), n) for n in names if (m := _CKPT_RE.match(n))
    )
    pinned: str | None = None
    ptr = os.path.join(directory, "LATEST")
    if os.path.exists(ptr):
        with open(ptr) as f:
            pinned = f.read().strip()
    deleted: list[str] = []
    for _, name in snaps[:-keep] if len(snaps) > keep else []:
        if name == pinned:
            continue
        path = os.path.join(directory, name)
        try:
            os.unlink(path)
            deleted.append(path)
        except FileNotFoundError:
            pass  # concurrent gc — already gone
    if deleted:
        obs.emit("checkpoint_gc", directory=directory, deleted=len(deleted),
                 keep=keep)
    return deleted


def latest_checkpoint(directory: str) -> str | None:
    ptr = os.path.join(directory, "LATEST")
    if not os.path.exists(ptr):
        return None
    with open(ptr) as f:
        name = f.read().strip()
    path = os.path.join(directory, name)
    return path if os.path.exists(path) else None


def peek_meta(path: str) -> dict[str, Any]:
    """Read only a checkpoint's metadata ``{step, config_hash, extra}`` —
    npz members load lazily, so this never touches the state arrays.
    Cheap enough for resume-point probing and for bench.py's partial-run
    accounting."""
    with np.load(path) as z:
        return json.loads(bytes(z[_META_KEY]).decode())


# --------------------------------------------------------------------------
# Versioned array directories (the serving-artifact substrate, ISSUE 8).
#
# ``.npz`` snapshots are zip containers: loading one decompresses every
# member into fresh host memory, which is exactly wrong for a long-lived
# server that wants the postings tables paged in on demand.  This second
# format keeps the SAME metadata schema ({step, config_hash, extra}) and
# the SAME atomic-pointer discipline, but stores each array as a bare
# ``<name>.npy`` inside a ``v%04d`` directory — ``np.load(mmap_mode="r")``
# then maps the file instead of copying it, so N server processes share
# one page cache and startup touches no array bytes at all.
# --------------------------------------------------------------------------


def save_array_dir(
    directory: str,
    version: int,
    arrays: dict[str, np.ndarray],
    config_hash: str,
    extra: dict[str, Any] | None = None,
) -> str:
    """Atomically write ``v{version:04d}/`` with one mmap-loadable ``.npy``
    per array plus a ``META.json`` sidecar; flips the LATEST pointer last,
    so a reader never sees a half-written version.  Returns the version
    directory path."""
    os.makedirs(directory, exist_ok=True)
    name = f"v{version:04d}"
    final = os.path.join(directory, name)
    if os.path.exists(final):
        raise FileExistsError(f"artifact version already exists: {final}")
    tmp = tempfile.mkdtemp(dir=directory, prefix=f".{name}.")
    try:
        for key, value in arrays.items():
            np.save(os.path.join(tmp, f"{key}.npy"), np.asarray(value))
        meta = {"step": int(version), "config_hash": config_hash,
                "extra": extra or {}}
        with open(os.path.join(tmp, "META.json"), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
            f.write("\n")
        # fsync every member + the staged dir + the parent: the dir must
        # appear whole AND durable before the LATEST flip names it
        durable_replace(tmp, final)
    finally:
        if os.path.exists(tmp):
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    _write_pointer(directory, name)
    nbytes = sum(
        os.path.getsize(os.path.join(final, f)) for f in os.listdir(final)
    )
    obs.emit("artifact_save", path=final, version=int(version), bytes=nbytes)
    obs.counter("artifact_saves")
    return final


def latest_array_dir(directory: str) -> str | None:
    """Resolve the LATEST pointer to a version directory (None when the
    directory holds no committed version)."""
    ptr = os.path.join(directory, "LATEST")
    if not os.path.exists(ptr):
        return None
    with open(ptr) as f:
        name = f.read().strip()
    path = os.path.join(directory, name)
    return path if os.path.isdir(path) else None


def next_version(directory: str) -> int:
    """1 + the highest committed version number in ``directory`` (1 when
    empty) — what a writer should pass to :func:`save_array_dir`."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return 1
    versions = [int(m.group(1)) for n in names if (m := _VDIR_RE.match(n))]
    return max(versions, default=0) + 1


def load_array_dir(
    path: str,
    expect_config_hash: str | None = None,
    *,
    mmap: bool = True,
) -> tuple[int, dict[str, np.ndarray], dict[str, Any]]:
    """Load a version directory: (version, arrays, extra).  With ``mmap``
    (the default) every array is an ``np.memmap`` view — pages fault in on
    first touch, nothing is copied up front.  Raises on config-hash
    mismatch, same contract as :func:`load_checkpoint`."""
    with open(os.path.join(path, "META.json")) as f:
        meta = json.load(f)
    if expect_config_hash is not None and meta["config_hash"] != expect_config_hash:
        raise ValueError(
            f"artifact {path} was written under config {meta['config_hash']}, "
            f"but current config is {expect_config_hash}; refusing to serve "
            "across semantic changes"
        )
    arrays = {
        n[:-4]: np.load(os.path.join(path, n),
                        mmap_mode="r" if mmap else None)
        for n in sorted(os.listdir(path))
        if n.endswith(".npy")
    }
    obs.emit("artifact_load", path=path, version=int(meta["step"]))
    return meta["step"], arrays, meta["extra"]


def load_checkpoint(
    path: str, expect_config_hash: str | None = None
) -> tuple[int, dict[str, np.ndarray], dict[str, Any]]:
    """Returns (step, arrays, extra). Raises on config-hash mismatch."""
    with np.load(path) as z:
        meta = json.loads(bytes(z[_META_KEY]).decode())
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
    if expect_config_hash is not None and meta["config_hash"] != expect_config_hash:
        raise ValueError(
            f"checkpoint {path} was written under config {meta['config_hash']}, "
            f"but current config is {expect_config_hash}; refusing to resume "
            "across semantic changes"
        )
    obs.emit("checkpoint_resume", path=path, step=int(meta["step"]))
    return meta["step"], arrays, meta["extra"]
