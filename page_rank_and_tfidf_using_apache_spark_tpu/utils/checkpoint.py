"""Checkpoint / resume (SURVEY.md §5.3/§5.4).

Reference counterpart: Spark lineage recomputation + ``RDD.checkpoint()``.
On TPU there is no lineage to replay, so recovery is restart-from-snapshot:
we save the live state arrays plus a step counter and the config hash, and
refuse to resume under a different semantic configuration.

Format: flat ``.npz`` (numpy) plus a JSON sidecar — deliberately dependency
-free and host-readable.  Writes are atomic (tmp file + rename) so a kill
mid-write never corrupts the latest checkpoint; the fault-injection test in
``tests/test_checkpoint.py`` exercises exactly that.

Mesh-shape tagging (ISSUE 5): sharded callers put ``devices=N`` in
``extra`` so a snapshot records which mesh wrote it, but the *payload* is
always logical global state (the [n] rank vector, the accumulated DF/TF
parts) — never per-device shards.  That is what makes checkpoints readable
across elastic mesh shrinks: a snapshot written by an 8-device run resumes
on 4, 1, or the CPU backend unchanged, and ``config_hash`` rightly ignores
topology because device count is operational, not semantic.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs


_META_KEY = "__ckpt_meta__"
_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.npz$")


def save_checkpoint(
    directory: str,
    step: int,
    arrays: dict[str, np.ndarray],
    config_hash: str,
    extra: dict[str, Any] | None = None,
    keep: int | None = None,
) -> str:
    """Atomically write ``step``'s state; returns the checkpoint path.

    ``keep`` bounds how many ``.npz`` snapshots stay on disk (oldest pruned
    after the LATEST pointer flips); None reads ``GRAFT_CKPT_KEEP``
    (default 8), and 0 keeps everything.
    """
    os.makedirs(directory, exist_ok=True)
    meta = {"step": int(step), "config_hash": config_hash, "extra": extra or {}}
    path = os.path.join(directory, f"ckpt_{step:08d}.npz")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(
                f,
                **{k: np.asarray(v) for k, v in arrays.items()},
                **{_META_KEY: np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)},
            )
        os.replace(tmp, path)  # atomic on POSIX
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    # "latest" pointer, also atomic — and with the same tmp hygiene as the
    # payload write: a failure between mkstemp and replace must not leak
    # the tempfile (it previously did).
    ptr = os.path.join(directory, "LATEST")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(os.path.basename(path))
        os.replace(tmp, ptr)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        nbytes = None  # a concurrent gc may beat us to it — tolerated above
    obs.emit("checkpoint_save", path=path, step=int(step), bytes=nbytes)
    obs.counter("checkpoint_saves")
    if keep is None:
        keep = int(os.environ.get("GRAFT_CKPT_KEEP", 8))
    if keep > 0:
        gc_checkpoints(directory, keep=keep)
    return path


def gc_checkpoints(directory: str, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` snapshots (by step number).

    The file the LATEST pointer names is always kept, whatever its step —
    a resumable run must never have its pointer dangling.  Returns the
    deleted paths (for logging/tests).
    """
    if keep <= 0:
        raise ValueError(f"keep must be >= 1, got {keep}")
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    snaps = sorted(
        (int(m.group(1)), n) for n in names if (m := _CKPT_RE.match(n))
    )
    pinned: str | None = None
    ptr = os.path.join(directory, "LATEST")
    if os.path.exists(ptr):
        with open(ptr) as f:
            pinned = f.read().strip()
    deleted: list[str] = []
    for _, name in snaps[:-keep] if len(snaps) > keep else []:
        if name == pinned:
            continue
        path = os.path.join(directory, name)
        try:
            os.unlink(path)
            deleted.append(path)
        except FileNotFoundError:
            pass  # concurrent gc — already gone
    if deleted:
        obs.emit("checkpoint_gc", directory=directory, deleted=len(deleted),
                 keep=keep)
    return deleted


def latest_checkpoint(directory: str) -> str | None:
    ptr = os.path.join(directory, "LATEST")
    if not os.path.exists(ptr):
        return None
    with open(ptr) as f:
        name = f.read().strip()
    path = os.path.join(directory, name)
    return path if os.path.exists(path) else None


def peek_meta(path: str) -> dict[str, Any]:
    """Read only a checkpoint's metadata ``{step, config_hash, extra}`` —
    npz members load lazily, so this never touches the state arrays.
    Cheap enough for resume-point probing and for bench.py's partial-run
    accounting."""
    with np.load(path) as z:
        return json.loads(bytes(z[_META_KEY]).decode())


def load_checkpoint(
    path: str, expect_config_hash: str | None = None
) -> tuple[int, dict[str, np.ndarray], dict[str, Any]]:
    """Returns (step, arrays, extra). Raises on config-hash mismatch."""
    with np.load(path) as z:
        meta = json.loads(bytes(z[_META_KEY]).decode())
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
    if expect_config_hash is not None and meta["config_hash"] != expect_config_hash:
        raise ValueError(
            f"checkpoint {path} was written under config {meta['config_hash']}, "
            f"but current config is {expect_config_hash}; refusing to resume "
            "across semantic changes"
        )
    obs.emit("checkpoint_resume", path=path, step=int(meta["step"]))
    return meta["step"], arrays, meta["extra"]
