from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    DanglingMode,
    IdfMode,
    PageRankConfig,
    RankInit,
    TfMode,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
    MetricsRecorder,
    Timer,
    logger,
)

__all__ = [
    "DanglingMode",
    "IdfMode",
    "PageRankConfig",
    "RankInit",
    "TfMode",
    "TfidfConfig",
    "MetricsRecorder",
    "Timer",
    "logger",
]
