"""Backend-provenance guard for measured cost artifacts.

The repo's cost artifacts (``xla_cost_tpu.json``, ``gather_micro_tpu.json``,
``breakdown_tpu.json``) drive kernel design AND the tier-3 intensity
ratchet (analysis/cost.py).  The round-5 failure mode this module exists
for: the TPU tunnel goes down, a tool re-runs on the CPU backend, and a
CPU-measured table silently replaces a TPU-measured one — after which
every consumer (including CI gates) reasons from numbers measured on the
wrong machine.

Two rules, enforced at write time:

- every artifact is stamped with the ``backend`` it was measured on
  (uniformly, by this helper — not ad hoc per tool);
- a tool may not overwrite an artifact stamped ``"backend": "tpu"`` with a
  record measured on any other backend unless the operator passes
  ``--force`` (the tools wire that flag through ``force=``).

Stdlib-only so the tools can import it before jax is up.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path


class ProvenanceError(RuntimeError):
    """Refusing to overwrite a TPU-measured artifact with a non-TPU run."""


def read_backend(path: str | Path) -> str | None:
    """Backend stamp of an existing artifact (None: missing/unreadable)."""
    try:
        record = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    backend = record.get("backend")
    return str(backend) if backend is not None else None


def check_overwrite(
    path: str | Path | None, backend: str, *, force: bool = False
) -> None:
    """Raise :class:`ProvenanceError` when writing a ``backend``-measured
    record to ``path`` would downgrade a TPU-stamped artifact (and
    ``force`` is not set).  The tools call this right after the backend is
    known — BEFORE spending minutes measuring — so a doomed run fails
    fast; :func:`write_artifact` re-checks at write time regardless."""
    if path is None:
        return
    existing = read_backend(path)
    if existing == "tpu" and backend != "tpu" and not force:
        raise ProvenanceError(
            f"{path} records a TPU-measured run but this run measures on "
            f"backend {backend!r}; refusing to overwrite the TPU baseline "
            "(re-run on the TPU, write to a different --out, or pass "
            "--force to downgrade it deliberately)"
        )


def write_artifact(
    path: str | Path | None,
    record: dict,
    *,
    backend: str,
    force: bool = False,
) -> dict:
    """Stamp ``record["backend"]`` and write it as one JSON line.

    Refuses (``ProvenanceError``) to overwrite an artifact whose stamp is
    ``"tpu"`` with a record measured on a different backend, unless
    ``force``.  ``path=None`` stamps without writing (tools always print
    the record to stdout regardless).  Returns the stamped record.
    """
    record = {"backend": backend, **record}
    if path is None:
        return record
    check_overwrite(path, backend, force=force)
    # tmp + atomic rename, never an in-place truncate-and-rewrite: a kill
    # mid-write must leave the previous (possibly TPU-stamped) record
    # intact, not a torn JSON that read_backend() calls unreadable — the
    # graftlint tier-5 atomic-write-drift class
    target = Path(path)
    fd, tmp = tempfile.mkstemp(dir=str(target.parent) or ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(record) + "\n")
        os.replace(tmp, str(target))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return record
