"""ctypes bindings to the native C++ host kernels (``native/fastio.cpp``).

Reference counterpart: the JVM/native machinery under Spark (netty, Tungsten,
codec JNI — SURVEY.md §2 native-code note).  The rebuild's device-side native
layer is XLA itself; this module is the *host*-side native layer: the
tokenizer+hasher and edge-list parser, the two ingest loops SURVEY.md §7
flags as Python bottlenecks at Wikipedia / soc-LiveJournal1 scale.

Every entry point degrades gracefully: if the shared library is missing and
cannot be built (no g++), callers get ``None`` and fall back to the numpy
implementations — bit-identical results, just slower.  ``tests/test_native.py``
pins C++ == numpy on the same inputs.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "fastio.cpp")
_BUILD_DIR = os.path.join(_HERE, "native", "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libfastio.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _load() -> ctypes.CDLL | None:
    """Build (once) and load the shared library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SRC):
                _lib_failed = True
                return None
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(  # graftlint: disable=blocking-under-lock (build-once guard: the lock is held across the g++ build ON PURPOSE so concurrent loaders wait for one build instead of racing duplicate compilers at the same .so path)
                    ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _LIB_PATH],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except (OSError, subprocess.CalledProcessError, AttributeError):
            # AttributeError: a stale prebuilt .so missing a newer symbol —
            # fall back to numpy rather than crash every ingest call.
            _lib_failed = True
    return _lib


def _declare(lib: ctypes.CDLL) -> None:
    c_i64 = ctypes.c_int64
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)

    lib.parse_edges_count.argtypes = [p_u8, c_i64]
    lib.parse_edges_count.restype = c_i64
    lib.parse_edges_fill.argtypes = [p_u8, c_i64, p_i64, p_i64]
    lib.parse_edges_fill.restype = c_i64

    lib.sort_dedup_edges.argtypes = [p_i64, p_i64, c_i64, c_i64]
    lib.sort_dedup_edges.restype = c_i64

    lib.tokenize_hash_count.argtypes = [p_u8, c_i64, p_i64, c_i64, c_i64, c_i64, c_i64]
    lib.tokenize_hash_count.restype = c_i64
    lib.tokenize_hash_fill.argtypes = [
        p_u8, c_i64, p_i64, c_i64, c_i64, c_i64, c_i64, c_i64, p_i32, p_i32, p_i32,
    ]
    lib.tokenize_hash_fill.restype = c_i64


def available() -> bool:
    return _load() is not None


def parse_edge_file(path: str) -> np.ndarray | None:
    """SNAP edge file → int64 [E, 2] array of (src, dst); None if native
    layer unavailable (caller falls back to numpy parse)."""
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    if data.size == 0:
        return np.empty((0, 2), dtype=np.int64)
    buf = data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    n = lib.parse_edges_count(buf, data.size)
    if n < 0:
        return None
    src = np.empty(n, dtype=np.int64)
    dst = np.empty(n, dtype=np.int64)
    got = lib.parse_edges_fill(
        buf, data.size,
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if got != n:
        return None
    return np.stack([src, dst], axis=1)


def sort_dedup_edges(
    src: np.ndarray, dst: np.ndarray, *, dedup: bool = True
) -> tuple[np.ndarray, np.ndarray] | None:
    """(dst, src)-radix-sort + optional dedup of compacted int64 edge arrays
    in C++ (the graph-builder hot step); None if the native layer is
    unavailable or ids exceed 2^31 (caller falls back to np.lexsort).

    MUTATES ``src``/``dst`` in place when they are already contiguous int64
    (the from_edges call site owns fresh astype copies; at soc-LiveJournal1
    scale a defensive copy would be an extra ~1 GB).  On failure (-1) the
    inputs are untouched — validation happens before any write."""
    lib = _load()
    if lib is None or src.size == 0:
        return None
    src_c = np.ascontiguousarray(src, dtype=np.int64)
    dst_c = np.ascontiguousarray(dst, dtype=np.int64)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    n = lib.sort_dedup_edges(
        src_c.ctypes.data_as(p_i64), dst_c.ctypes.data_as(p_i64),
        src_c.size, int(dedup),
    )
    if n < 0:
        return None
    return src_c[:n], dst_c[:n]


def tokenize_and_hash(
    docs,
    *,
    vocab_bits: int,
    ngram: int,
    lowercase: bool,
    min_token_len: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Tokenize + FNV-1a-hash a batch of docs in C++.

    Returns (doc_ids int32 [T], term_ids int32 [T], doc_lengths int32 [D])
    matching the numpy path in io/text.py exactly, or None if unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    enc = [d.encode("utf-8") for d in docs]
    lens = np.fromiter((len(b) for b in enc), dtype=np.int64, count=len(enc))
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8) if enc else np.empty(0, np.uint8)
    # Guard ctypes against NULL data pointers from zero-length arrays.
    blob = np.ascontiguousarray(blob) if blob.size else np.zeros(1, np.uint8)
    lens_c = np.ascontiguousarray(lens) if lens.size else np.zeros(1, np.int64)

    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)

    total = lib.tokenize_hash_count(
        blob.ctypes.data_as(p_u8), int(blob.size if enc else 0),
        lens_c.ctypes.data_as(p_i64), len(enc),
        int(ngram), int(lowercase), int(min_token_len),
    )
    if total < 0:
        return None
    doc_ids = np.empty(total, dtype=np.int32)
    term_ids = np.empty(total, dtype=np.int32)
    doc_lengths = np.empty(max(len(enc), 1), dtype=np.int32)
    got = lib.tokenize_hash_fill(
        blob.ctypes.data_as(p_u8), int(blob.size if enc else 0),
        lens_c.ctypes.data_as(p_i64), len(enc),
        int(ngram), int(lowercase), int(min_token_len), int(vocab_bits),
        doc_ids.ctypes.data_as(p_i32) if total else ctypes.cast(None, p_i32),
        term_ids.ctypes.data_as(p_i32) if total else ctypes.cast(None, p_i32),
        doc_lengths.ctypes.data_as(p_i32),
    )
    if got != total:
        return None
    return doc_ids, term_ids, doc_lengths[: len(enc)]
