from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
    PageRankResult,
    run_pagerank,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    TfidfOutput,
    run_tfidf,
    run_tfidf_streaming,
)

__all__ = [
    "PageRankResult",
    "run_pagerank",
    "TfidfOutput",
    "run_tfidf",
    "run_tfidf_streaming",
]
