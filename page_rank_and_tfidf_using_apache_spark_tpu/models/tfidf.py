"""TF-IDF model drivers: batch and streaming ingest.

Reference counterpart (SURVEY.md A6–A10, §3.2): the ``tfidf.py`` Spark
driver — tokenize/flatMap, TF and DF reduceByKey passes, IDF, join, save.
The batch path here is one device pipeline call; the streaming path
(BASELINE.json:11 "English Wikipedia ~6M docs, streaming ingest") feeds
fixed-shape token chunks through a once-compiled kernel, accumulating the
DF vector and doc count on device and spilling per-chunk TF counts to host,
then applies IDF in a second pass — the two-pass structure Spark gets from
its separate TF and DF shuffles, minus the shuffles.

Checkpointing (SURVEY.md §5.4): every ``checkpoint_every`` chunks the
accumulated ``(df, n_docs, chunk_index, tf-counts-so-far)`` state is
snapshotted atomically; resume skips already-ingested chunks.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import ingest as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import elastic
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfidfConfig, TfMode, ensure_dtype_support
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


@dataclasses.dataclass(frozen=True)
class TfidfOutput:
    """Host-side sparse TF-IDF matrix in COO form, sorted by (term, doc),
    plus the dense DF/IDF tables — the reference's saved A10 output."""

    n_docs: int
    vocab_bits: int
    doc: np.ndarray  # int32 [nnz]
    term: np.ndarray  # int32 [nnz]
    weight: np.ndarray  # f[nnz]
    df: np.ndarray  # f[vocab]
    idf: np.ndarray  # f[vocab]
    metrics: MetricsRecorder
    # Raw per-pair counts + per-doc lengths ride along so a second
    # weighting over the SAME postings (BM25 — dataflow/bm25.py) needs no
    # corpus re-pass.  None on outputs built before this field existed.
    count: np.ndarray | None = None  # f[nnz]
    doc_lengths: np.ndarray | None = None  # int32 [n_docs]

    @property
    def nnz(self) -> int:
        return int(self.doc.shape[0])

    def to_dense(self) -> np.ndarray:
        """[n_docs, vocab] dense matrix — tests/small corpora only."""
        out = np.zeros((self.n_docs, 1 << self.vocab_bits), dtype=self.weight.dtype)
        out[self.doc, self.term] = self.weight
        return out


def run_tfidf(
    docs: Sequence[str],
    cfg: TfidfConfig,
    *,
    metrics: MetricsRecorder | None = None,
    doc_names: Sequence[str] | None = None,
) -> TfidfOutput:
    """Batch TF-IDF: tokenize on host, one compiled device pipeline."""
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    # tokenize_corpus opens its own "io.tokenize" span — no wrapper here
    with Timer() as t_tok:
        corpus = tio.tokenize_corpus(
            docs,
            vocab_bits=cfg.vocab_bits,
            ngram=cfg.ngram,
            lowercase=cfg.lowercase,
            min_token_len=cfg.min_token_len,
            doc_names=doc_names,
        )
    metrics.record(event="tokenize", docs=corpus.n_docs, tokens=corpus.n_tokens, secs=t_tok.elapsed)

    with Timer() as t_dev, obs.span("tfidf.pipeline"):
        result = ops.tfidf_pipeline(
            jnp.asarray(corpus.doc_ids),
            jnp.asarray(corpus.term_ids),
            jnp.asarray(corpus.doc_lengths),
            n_docs=max(corpus.n_docs, 1),
            vocab=cfg.vocab_size,
            tf_mode=cfg.tf_mode,
            idf_mode=cfg.idf_mode,
            l2_normalize=cfg.l2_normalize,
        )
        rx.block_until_ready(result, site="tfidf_batch_sync", metrics=metrics)
    n_pairs = int(result.n_pairs)
    metrics.record(
        event="pipeline", pairs=n_pairs, secs=t_dev.elapsed,
        tokens_per_sec=corpus.n_tokens / t_dev.elapsed if t_dev.elapsed > 0 else float("inf"),
    )
    return TfidfOutput(
        n_docs=corpus.n_docs,
        vocab_bits=cfg.vocab_bits,
        doc=np.asarray(result.doc[:n_pairs]),
        term=np.asarray(result.term[:n_pairs]),
        weight=np.asarray(result.weight[:n_pairs]),
        df=np.asarray(result.df),
        idf=np.asarray(result.idf),
        metrics=metrics,
        count=np.asarray(result.count[:n_pairs]),
        doc_lengths=np.asarray(corpus.doc_lengths),
    )


# The fixed-shape capacity policy moved into the dataflow core
# (dataflow/ingest.py) with the rest of the chunked-ingest machinery; the
# re-export keeps this module the policy's public address for the serving
# micro-batcher and the lint registry's shape matrices.
grow_chunk_cap = dflow.grow_chunk_cap


def stream_pad_plan(
    raw_token_counts: Sequence[int], cap: int = 0
) -> list[tuple[str, float]]:
    """Static padding-waste plan of the streaming ingest: run the raw
    per-chunk token counts through the REAL :func:`grow_chunk_cap` policy
    (no dispatch, no device) and return ``[("stream", pad_frac)]`` where
    ``pad_frac`` is the fraction of dispatched token slots that are padding
    across the whole stream.  This is the tier-3 pad_frac surface for the
    chunk-ingest entry points (analysis/cost.py), the TF-IDF counterpart of
    ``parallel.pagerank_sharded.plan_partition``."""
    import logging

    log = logging.getLogger("pr_tfidf_tpu")
    was_disabled = log.disabled
    log.disabled = True  # cap-bump log lines are production telemetry
    try:
        metrics = MetricsRecorder()
        total_raw = 0
        total_cap = 0
        for raw in raw_token_counts:
            cap, _ = grow_chunk_cap(raw, cap, metrics)
            total_raw += int(raw)
            total_cap += cap
    finally:
        log.disabled = was_disabled
    pad_frac = (total_cap - total_raw) / max(total_cap, 1)
    return [("stream", pad_frac)]


@dataclasses.dataclass
class IngestState:
    """Accumulated streaming-ingest state, shared by the streaming and
    sharded paths: exactly what a per-chunk checkpoint snapshots, so a
    killed run resumes at the first unprocessed chunk with zero rework.

    ``ingest_secs`` is cumulative wall time *as of the last checkpoint*,
    carried across resumes — it is what makes a partial run's tokens/sec a
    real, comparable metric (bench.py's ``"partial": true`` record).
    """

    df_total: np.ndarray
    chunk_index: int = 0  # chunks fully ingested (== next chunk to process)
    n_docs: int = 0
    n_tokens: int = 0
    ingest_secs: float = 0.0
    parts: list = dataclasses.field(default_factory=list)  # (doc, term, count)
    doc_length_parts: list = dataclasses.field(default_factory=list)


def resume_point(cfg: TfidfConfig) -> int:
    """Chunk index a ``resume=True`` run will start at (0 = from scratch)
    — cheap (reads only checkpoint metadata), so callers that can seek
    their corpus source may skip materializing the ingested prefix
    (io.text.iter_corpus_chunks ``skip_chunks=``)."""
    if not cfg.checkpoint_dir:
        return 0
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    if latest is None:
        return 0
    return int(ckpt.peek_meta(latest)["step"])


def resume_ingest(cfg: TfidfConfig, metrics: MetricsRecorder) -> IngestState:
    """Load the latest ingest checkpoint (streaming and sharded paths share
    the format); a fresh zero state when no checkpoint exists."""
    if not cfg.checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")
    fresh = IngestState(df_total=np.zeros(cfg.vocab_size, cfg.dtype))
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    if latest is None:
        return fresh
    chunk_index, arrays, extra = ckpt.load_checkpoint(latest, cfg.config_hash())
    st = IngestState(
        df_total=arrays["df"],
        chunk_index=int(chunk_index),
        n_docs=int(extra["n_docs"]),
        n_tokens=int(extra.get("n_tokens", 0)),
        ingest_secs=float(extra.get("ingest_secs", 0.0)),
        parts=[(arrays["doc"], arrays["term"], arrays["count"])],
        doc_length_parts=[arrays["doc_lengths"]],
    )
    metrics.record(event="resume", path=latest, chunk=st.chunk_index, docs=st.n_docs)
    return st


def save_ingest_checkpoint(
    cfg: TfidfConfig, metrics: MetricsRecorder, st: IngestState,
    extra_meta: dict | None = None,
) -> None:
    """Snapshot accumulated ingest state, compacting the part lists in
    place so host memory stays flat across checkpoints.  ``extra_meta``
    rides along in the checkpoint metadata (the sharded path tags
    ``devices=N`` so a snapshot records which mesh shape wrote it); the
    payload itself is mesh-shape-independent — accumulated global DF and
    TF parts — so any device count can resume from it."""
    doc_a, term_a, count_a = (np.concatenate(x) for x in zip(*st.parts))
    st.parts = [(doc_a, term_a, count_a)]
    st.doc_length_parts = [np.concatenate(st.doc_length_parts)]
    path = ckpt.save_checkpoint(
        cfg.checkpoint_dir,
        st.chunk_index,
        {
            "df": st.df_total, "doc": doc_a, "term": term_a, "count": count_a,
            "doc_lengths": st.doc_length_parts[0],
        },
        cfg.config_hash(),
        extra={
            "n_docs": st.n_docs,
            "n_tokens": st.n_tokens,
            "ingest_secs": round(st.ingest_secs, 3),
            **(extra_meta or {}),
        },
    )
    metrics.record(event="checkpoint", path=path, chunk=st.chunk_index)


# Below this many accumulated pairs the numpy finalize wins (no dispatch /
# transfer overhead); above it the device path's fused elementwise math and
# segment reductions do (VERDICT r1 item 5).  Tests override to 0.
DEVICE_FINALIZE_MIN_NNZ = 1 << 20


def finalize_tfidf(
    st: IngestState,
    cfg: TfidfConfig,
    metrics: MetricsRecorder,
) -> TfidfOutput:
    """Second pass shared by the streaming and sharded ingest paths: IDF
    join + TF weighting + optional L2 normalize.  Small accumulations run in
    numpy; at scale the per-pair math and the per-doc L2 reduction run on
    device (ops.finalize_weights)."""
    dtype = cfg.dtype
    n_docs = st.n_docs
    df_total = st.df_total
    if not st.parts:
        z = np.zeros(0, np.int32)
        return TfidfOutput(0, cfg.vocab_bits, z, z, np.zeros(0, dtype),
                           df_total, np.zeros(cfg.vocab_size, dtype), metrics)

    doc_a = np.concatenate([p[0] for p in st.parts])
    term_a = np.concatenate([p[1] for p in st.parts])
    count_a = np.concatenate([p[2] for p in st.parts]).astype(dtype)
    doc_lengths = np.concatenate(st.doc_length_parts)

    with obs.span("tfidf.finalize", nnz=int(doc_a.shape[0])):
        idf = rx.device_get(
            ops.idf_vector(jnp.asarray(df_total), float(max(n_docs, 1)), cfg.idf_mode),
            site="tfidf_finalize_sync", metrics=metrics,
            checkpoint_dir=cfg.checkpoint_dir,
        )
        with Timer() as t_fin:
            if doc_a.shape[0] >= DEVICE_FINALIZE_MIN_NNZ:
                weight = rx.device_get(ops.finalize_weights(
                    jnp.asarray(doc_a), jnp.asarray(count_a),
                    jnp.asarray(doc_lengths), jnp.asarray(idf[term_a]),
                    n_docs=max(n_docs, 1), tf_mode=cfg.tf_mode,
                    l2_normalize=cfg.l2_normalize,
                ), site="tfidf_finalize_sync", metrics=metrics,
                   checkpoint_dir=cfg.checkpoint_dir)
                where = "device"
            else:
                if cfg.tf_mode is TfMode.RAW:
                    tf = count_a
                elif cfg.tf_mode is TfMode.FREQ:
                    tf = count_a / np.maximum(doc_lengths[doc_a].astype(dtype), 1.0)
                else:  # LOGNORM
                    tf = np.where(count_a > 0, 1.0 + np.log(np.maximum(count_a, 1.0)),
                                  0.0).astype(dtype)
                weight = tf * idf[term_a]
                if cfg.l2_normalize:
                    sq = np.zeros(n_docs, dtype)
                    np.add.at(sq, doc_a, weight * weight)
                    weight = weight / np.sqrt(np.maximum(sq, 1e-30))[doc_a]
                where = "host"
    metrics.record(event="finalize", where=where, nnz=int(doc_a.shape[0]),
                   secs=t_fin.elapsed)
    metrics.scalar("n_docs", n_docs)
    metrics.scalar("nnz", int(doc_a.shape[0]))
    return TfidfOutput(
        n_docs=n_docs, vocab_bits=cfg.vocab_bits,
        doc=doc_a, term=term_a, weight=weight.astype(dtype),
        df=df_total, idf=idf, metrics=metrics,
        count=count_a, doc_lengths=doc_lengths,
    )


def _pad_chunk(
    corpus: tio.TokenizedCorpus, cap: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    t = corpus.n_tokens
    doc_ids = np.zeros(cap, np.int32)
    term_ids = np.zeros(cap, np.int32)
    valid = np.zeros(cap, bool)
    doc_ids[:t] = corpus.doc_ids
    term_ids[:t] = corpus.term_ids
    valid[:t] = True
    return doc_ids, term_ids, valid


def _tokenized_chunks(
    doc_chunks: Iterable[Sequence[str]],
    cfg: TfidfConfig,
    start_chunk: int,
    n_docs0: int,
) -> Iterator[tuple[int, tio.TokenizedCorpus]]:
    """Tokenize chunks in order, assigning globally unique doc ids;
    skips the already-ingested prefix on resume.

    Resume bookkeeping is in chunk *indices*, so a caller re-chunking the
    corpus differently between runs would silently skip the wrong
    documents.  When the skipped prefix arrives as real chunks (not the
    empty placeholders of ``iter_corpus_chunks(skip_chunks=...)``, which
    validates on its own side), its document count must equal the
    checkpoint's ``n_docs`` — mismatch fails loudly.
    """
    n_docs = n_docs0
    skipped_docs = 0
    for i, docs in enumerate(doc_chunks):
        if i < start_chunk:
            skipped_docs += len(docs)
            if i == start_chunk - 1 and skipped_docs not in (0, n_docs0):
                raise ValueError(
                    f"resume chunking mismatch: the skipped prefix of "
                    f"{start_chunk} chunk(s) holds {skipped_docs} documents "
                    f"but the checkpoint ingested {n_docs0}; rerun with the "
                    "original chunking (e.g. the same --chunk-docs)"
                )
            continue  # already ingested before the resume point
        # tokenize_corpus opens its own "io.tokenize" span (also on the
        # prefetch thread) — no wrapper here
        corpus = tio.tokenize_corpus(
            docs,
            vocab_bits=cfg.vocab_bits,
            ngram=cfg.ngram,
            lowercase=cfg.lowercase,
            min_token_len=cfg.min_token_len,
            doc_id_offset=n_docs,
        )
        n_docs += corpus.n_docs
        yield i, corpus


# Commit-barrier interval (in chunks) for streaming runs WITHOUT
# checkpointing: bounds how many drained chunks' host copies
# retain_until_commit may hold (the elastic rung replays at most this
# span after a device loss).  With 2^18-token chunks this caps retention
# near 16M tokens of int32 pairs — flat host memory, rare drain bubbles.
_RETAIN_COMMIT_EVERY = 16


def run_tfidf_streaming(
    doc_chunks: Iterable[Sequence[str]],
    cfg: TfidfConfig,
    *,
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> TfidfOutput:
    """Streaming TF-IDF over an iterator of document chunks.

    Documents never span chunks, so per-chunk run-length DF increments add
    up to the exact global DF.  Chunk token arrays are padded to a fixed
    capacity (``cfg.chunk_tokens``, or the first chunk's size rounded up to
    a power of two) so the device kernel compiles once; an oversized chunk
    bumps the capacity with a logged recompile (SURVEY.md §7).

    The loop is a four-stage software pipeline (SURVEY.md §5.7, ISSUE 10):
    a background thread tokenizes up to ``cfg.prefetch`` chunks ahead; a
    **transfer thread** pads each chunk and issues its ``jax.device_put``
    (the H2D staging stage, chaos/retry site ``ingest_h2d_put``) holding
    at most ``cfg.pipeline_depth`` staged chunks of device memory — chunk
    N+1's transfer runs under chunk N's compute; the main thread
    dispatches the once-compiled kernel against pre-staged device buffers
    only and defers each chunk's host pull until ``cfg.prefetch`` launches
    are in flight.  ``prefetch=0, pipeline_depth=0`` is fully serial: no
    background threads and every chunk syncs before the next launches.
    ``cfg.pack_target_tokens > 0`` additionally re-packs the incoming
    chunking to fill the compiled capacity (padding, not scheduling, is
    most of the measured streaming-vs-batch gap).  Results are
    bit-identical at every depth — only scheduling changes.

    The DF accumulator is an **ingest carry**: a device-resident vector
    threaded through :func:`ops.tfidf.chunk_counts_carry` with its buffer
    donated, so XLA updates it in place every chunk and the host never
    pulls DF per chunk.  DF reaches the host only at *commit points* —
    checkpoint saves and finalize — behind the drain-before-commit
    barrier (``dataflow.fixpoint.commit_barrier``): a snapshot can only be
    written once every in-flight launch has drained, so it never contains
    DF contributions from chunks it does not record as ingested.

    Device loss anywhere in the pipeline (an H2D put on the transfer
    thread included — chaos site ``ingest_h2d_put``) walks the single-chip
    elastic rung: the loss is acknowledged, host state rolls back to the
    last commit point, and the pipeline replays the uncommitted span from
    the host copies it retained — the tokenized chunks — onto the CPU
    backend, byte-identically.  Committed chunks are never reprocessed.
    """
    ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    vocab = cfg.vocab_size
    dtype = cfg.dtype
    cap = cfg.chunk_tokens

    st = (resume_ingest(cfg, metrics) if resume
          else IngestState(df_total=np.zeros(vocab, dtype)))
    secs0 = st.ingest_secs
    run_started = time.perf_counter()
    last_ckpt = st.chunk_index
    # The device-resident DF carry (donated to every chunk dispatch; this
    # reference is always the LATEST carry, never a consumed one).
    df_dev = jnp.asarray(st.df_total)
    # None until a device loss: the elastic rung then pins every
    # subsequent put (and so every dispatch) to the CPU backend.
    target_dev = None

    if cfg.pack_target_tokens > 0:
        doc_chunks = dflow.pack_doc_chunks(
            doc_chunks, cfg.pack_target_tokens,
            estimate=dflow.ngram_estimator(cfg.ngram))
    source = _tokenized_chunks(doc_chunks, cfg, st.chunk_index, st.n_docs)

    # Rollback point for the elastic rung: what st looked like at the
    # last commit barrier.  Chunks drained after it have host TF parts
    # but their DF lives only in the (now dead) device carry — recovery
    # truncates them here and the pipeline replays their retained host
    # copies, so nothing is lost and nothing double-counts.
    committed: dict = {}

    def snap_commit() -> None:
        committed.update(
            parts=len(st.parts), dls=len(st.doc_length_parts),
            n_docs=st.n_docs, n_tokens=st.n_tokens, chunk=st.chunk_index,
        )

    snap_commit()

    def _put(arr):
        return (jax.device_put(arr, target_dev) if target_dev is not None
                else jax.device_put(arr))

    def stage_chunk(item):
        """H2D staging stage (transfer thread when pipeline_depth > 0):
        pad one tokenized chunk to the fixed capacity and issue its
        device transfers through the guarded staging site.  The item's
        host arrays stay retained by the pipeline until commit — the
        elastic rung re-stages from them."""
        nonlocal cap
        i, corpus = item
        cap, _ = grow_chunk_cap(corpus.n_tokens, cap, metrics, chunk=i)
        doc_ids, term_ids, valid = _pad_chunk(corpus, cap)
        d_doc, d_term, d_valid = dflow.staged_put(
            lambda: (_put(doc_ids), _put(term_ids), _put(valid)),
            metrics=metrics,
        )
        return (i, corpus, d_doc, d_term, d_valid)

    def launch(staged):
        """Dispatch the once-compiled kernel (async) against pre-staged
        device buffers only; the in-flight record carries what the drain
        needs to commit it."""
        nonlocal df_dev
        i, corpus, d_doc, d_term, d_valid = staged
        with Timer() as t:
            counts, df_dev = ops.chunk_counts_carry(
                d_doc, d_term, d_valid, df_dev, vocab=vocab,
            )  # async dispatch — no block here; df carry updated in place
        return (i, counts, corpus.doc_lengths,
                corpus.n_docs, corpus.n_tokens, t)

    def drain_one(rec):
        i, counts, doc_lengths, n_chunk_docs, n_tokens, t = rec
        with Timer() as t_sync, obs.span("tfidf.chunk", chunk=i):
            # Wait for this chunk's device results with ONE batched
            # device->host pull.  The old path paid five round-trips per
            # chunk (int(n_pairs) fence + three sliced np.asarray pulls +
            # the df pull) — at ~76 ms tunnel RTT that serialized the
            # whole streaming path (VERDICT.md round 5).  Pulling the
            # padded arrays whole costs a few MB of extra bytes but only
            # one round-trip; the slice happens on host.  (The DF vector is
            # no longer part of this pull at all — it stays on device as
            # the donated ingest carry until a commit point.)  The pull
            # runs under the resilience executor: a transient failure or
            # blown sync deadline re-issues the transfer (device buffers
            # are still live); exhaustion surfaces ResilienceExhausted
            # carrying the last chunk checkpoint to resume from.
            h_doc, h_term, h_count, h_n_pairs = rx.device_get(
                (counts.doc, counts.term, counts.count, counts.n_pairs),
                site="tfidf_chunk_sync", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )
            k = int(h_n_pairs)
            # .copy() so parts holds k-sized arrays, not views pinning the
            # whole cap-sized transfer buffer until finalize
            st.parts.append((h_doc[:k].copy(), h_term[:k].copy(), h_count[:k].copy()))
        st.doc_length_parts.append(doc_lengths)
        st.n_docs += n_chunk_docs
        st.n_tokens += n_tokens
        st.chunk_index = i + 1
        metrics.record(event="chunk", chunk=i, docs=st.n_docs, tokens=n_tokens,
                       pairs=k, dispatch_secs=round(t.elapsed, 6),
                       secs=t_sync.elapsed)
        obs.counter("tfidf.chunks")
        obs.histogram("tfidf.chunk_secs", t_sync.elapsed)

    def commit_df():
        # Pull the device DF carry into host state.  chunked_ingest calls
        # this only when no launch is in flight: the carry always reflects
        # every DISPATCHED chunk, so a mid-flight pull would commit DF for
        # chunks the state does not count as ingested.  Its own site (not
        # tfidf_chunk_sync): chaos schedules and retry tallies count
        # per-chunk drains, and a commit is not a chunk.
        with obs.span("tfidf.df_commit"):
            st.df_total = rx.device_get(
                df_dev, site="tfidf_df_commit", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            ).astype(dtype)
        snap_commit()

    def recover(exc, remaining, where):
        """Single-chip elastic rung for the staged pipeline: a
        device-attributed loss anywhere in it (H2D put on the transfer
        thread, dispatch, drain) is acknowledged, host state rolls back
        to the last commit point, the DF carry is rebuilt from committed
        host DF on the CPU backend, and the pipeline replays the
        uncommitted span from its retained host chunks (byte-identical
        order).  Anything else — elastic disabled, whole-backend faults
        with no device index — re-raises into the pre-existing ladder
        (ResilienceExhausted + checkpoint)."""
        nonlocal df_dev, target_dev
        lost = elastic.unwrap_device_loss(exc)
        idx = elastic.device_index(lost) if lost is not None else None
        if not elastic.enabled() or idx is None:
            raise exc
        elastic.health().mark_lost(idx)
        site = {"stage": dflow.H2D_PUT_SITE,
                "wait": dflow.H2D_WAIT_SITE}.get(where, "tfidf_chunk_sync")
        rerun = st.chunk_index - committed["chunk"]
        obs.emit("degraded", site=site, ladder="cpu",
                 salvage_chunk=committed["chunk"], rerun_chunks=rerun,
                 error=f"{type(exc).__name__}: {exc}"[:200])
        obs.counter("degraded")
        metrics.record(event="degraded", site=site, ladder="cpu",
                       salvage_chunk=committed["chunk"], rerun_chunks=rerun)
        with obs.span("tfidf.cpu_salvage", at_chunk=committed["chunk"],
                      rerun_chunks=rerun):
            del st.parts[committed["parts"]:]
            del st.doc_length_parts[committed["dls"]:]
            st.n_docs = committed["n_docs"]
            st.n_tokens = committed["n_tokens"]
            st.chunk_index = committed["chunk"]
            target_dev = jax.devices("cpu")[0]
            df_dev = jax.device_put(st.df_total, target_dev)
        return remaining

    def checkpoint_due() -> bool:
        if cfg.checkpoint_every > 0 and cfg.checkpoint_dir:
            return st.chunk_index - last_ckpt >= cfg.checkpoint_every
        # Checkpointing off: retain_until_commit would otherwise hold
        # every drained chunk's host copy until the single end-of-stream
        # commit — a second full-corpus copy.  A commit-only barrier (DF
        # pull + rollback-point re-snap, no snapshot file) every K chunks
        # keeps host memory flat at the cost of one pipeline drain per K.
        return st.chunk_index - last_ckpt >= _RETAIN_COMMIT_EVERY

    def save_ckpt():
        nonlocal last_ckpt
        last_ckpt = st.chunk_index
        if not (cfg.checkpoint_every > 0 and cfg.checkpoint_dir):
            return  # retention-bounding barrier: commit already ran
        st.ingest_secs = secs0 + (time.perf_counter() - run_started)
        save_ingest_checkpoint(cfg, metrics, st)
        # the save compacts st.parts in place — re-snap the rollback
        # point so its list indices match the compacted layout
        snap_commit()

    # The host pipeline — staged H2D double-buffering, bounded in-flight
    # launches, drain-before-commit checkpoints, background source
    # prefetch, elastic recovery — is the dataflow core's chunked_ingest
    # primitive; this driver only supplies the TF-IDF closures (and keeps
    # its guarded sites/spans byte-identical to the pre-port path).
    with obs.span("tfidf.stream", resume_chunk=st.chunk_index):
        dflow.chunked_ingest(
            source,
            stage=stage_chunk,
            launch=launch,
            drain=drain_one,
            commit=commit_df,
            ingest=cfg.ingest(),
            checkpoint_due=checkpoint_due,
            save_checkpoint=save_ckpt,
            recover=recover,
            retain_until_commit=True,
            metrics=metrics,
        )

    return finalize_tfidf(st, cfg, metrics)
