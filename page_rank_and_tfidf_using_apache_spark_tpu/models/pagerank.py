"""PageRank model driver: orchestration, checkpointing, metrics.

Reference counterpart (SURVEY.md A1/A4/A5): the ``pagerank.py`` driver —
``main(argv)`` building the graph, running the ``for i in range(iters)``
loop, collecting ranks.  Here the driver's only jobs are host-side: move the
graph to device once, launch the compiled loop, periodically snapshot state,
and emit structured per-segment metrics (SURVEY.md §5.5).  The numeric loop
itself is ops/pagerank.py, compiled to a single XLA program.

Checkpointing (SURVEY.md §5.3/§5.4): with ``checkpoint_every = k`` the run
executes in k-iteration compiled segments with an atomic snapshot of
``(ranks, iteration, config_hash)`` between segments — recovery is
restart-from-snapshot (there is no lineage to replay on TPU), exercised by
the kill/resume fault-injection test.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


@dataclasses.dataclass(frozen=True)
class PageRankResult:
    ranks: np.ndarray  # f[n_nodes], aligned with graph's compacted ids
    iterations: int  # iterations actually executed
    l1_delta: float  # L1 delta of the final iteration
    metrics: MetricsRecorder


def run_pagerank(
    graph: Graph,
    cfg: PageRankConfig,
    *,
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> PageRankResult:
    """Run PageRank per ``cfg`` on the default device (single-chip path;
    the sharded multi-chip path is parallel/pagerank_sharded.py)."""
    metrics = metrics or MetricsRecorder()
    n = graph.n_nodes
    if n == 0:
        return PageRankResult(np.zeros(0, cfg.dtype), 0, 0.0, metrics)

    dg = ops.put_graph(graph, cfg.dtype)
    e = jax.device_put(ops.restart_vector(n, cfg))
    ranks = np.asarray(ops.init_ranks(n, cfg))
    start_iter = 0

    if resume:
        if not cfg.checkpoint_dir:
            raise ValueError("resume=True requires checkpoint_dir")
        latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
        if latest is not None:
            start_iter, arrays, _ = ckpt.load_checkpoint(latest, cfg.config_hash())
            ranks = arrays["ranks"]
            metrics.record(event="resume", path=latest, start_iter=start_iter)

    ranks_dev = jax.device_put(ranks.astype(cfg.dtype))

    make = ops.make_spark_exact_runner if cfg.spark_exact else ops.make_pagerank_runner
    remaining = cfg.iterations - start_iter
    segment = (
        cfg.checkpoint_every
        if (cfg.checkpoint_every > 0 and not cfg.spark_exact and cfg.tol == 0.0)
        else remaining
    )

    done = start_iter
    last_delta = float("inf")
    while done < cfg.iterations:
        todo = min(segment, cfg.iterations - done)
        seg_cfg = dataclasses.replace(
            cfg, iterations=todo, checkpoint_every=0, checkpoint_dir=None
        )
        runner = make(n, seg_cfg)
        with Timer() as t:
            ranks_dev, iters, delta = runner(dg, ranks_dev, e)
            ranks_dev.block_until_ready()
        done += int(iters)
        last_delta = float(delta)
        metrics.record(
            iter=done,
            l1_delta=last_delta,
            secs=t.elapsed,
            iters_per_sec=int(iters) / t.elapsed if t.elapsed > 0 else float("inf"),
        )
        if cfg.checkpoint_every > 0 and cfg.checkpoint_dir and done < cfg.iterations:
            path = ckpt.save_checkpoint(
                cfg.checkpoint_dir,
                done,
                {"ranks": np.asarray(ranks_dev)},
                cfg.config_hash(),
            )
            metrics.record(event="checkpoint", path=path, iter=done)
        if cfg.tol > 0.0 and last_delta <= cfg.tol:
            break
        if todo == remaining and cfg.tol > 0.0:
            break  # while_loop runner already handled tol internally

    metrics.scalar("iterations", done)
    metrics.scalar("l1_delta", last_delta)
    return PageRankResult(
        ranks=np.asarray(ranks_dev), iterations=done, l1_delta=last_delta, metrics=metrics
    )
