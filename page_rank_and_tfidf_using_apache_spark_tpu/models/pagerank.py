"""PageRank model driver: orchestration, checkpointing, metrics.

Reference counterpart (SURVEY.md A1/A4/A5): the ``pagerank.py`` driver —
``main(argv)`` building the graph, running the ``for i in range(iters)``
loop, collecting ranks.  Here the driver's only jobs are host-side: move the
graph to device once, launch the compiled loop, periodically snapshot state,
and emit structured per-segment metrics (SURVEY.md §5.5).  The numeric loop
itself is ops/pagerank.py, compiled to a single XLA program.

Checkpointing (SURVEY.md §5.3/§5.4): with ``checkpoint_every = k`` the run
executes in k-iteration compiled segments with an atomic snapshot of
``(ranks, iteration, config_hash)`` between segments — recovery is
restart-from-snapshot (there is no lineage to replay on TPU), exercised by
the kill/resume fault-injection test.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import fixpoint as dflow
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.models import driver
from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import config
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


def put_graph_for(graph: Graph, cfg: PageRankConfig) -> ops.DeviceGraph:
    """``ops.put_graph`` with whatever static layout ``cfg.spmv_impl``
    needs (dense hybrid head rows, sort-shuffle buckets) built from the
    config's layout knobs.  Layout impls never read the raw edge arrays
    (the layout duplicates every edge), so their device copy is skipped."""
    layout = ops.layout_for_impl(cfg.spmv_impl)
    return ops.put_graph(
        graph, cfg.dtype,
        layout=layout,
        head_coverage=cfg.head_coverage,
        head_row_width=cfg.head_row_width,
        bucket_width=cfg.shuffle_bucket_width,
        keep_edge_arrays=layout is None,
    )


@dataclasses.dataclass(frozen=True)
class PageRankResult:
    ranks: np.ndarray  # f[n_nodes], aligned with graph's compacted ids
    iterations: int  # iterations actually executed
    l1_delta: float  # L1 delta of the final iteration
    metrics: MetricsRecorder


def run_pagerank(
    graph: Graph,
    cfg: PageRankConfig,
    *,
    metrics: MetricsRecorder | None = None,
    resume: bool = False,
) -> PageRankResult:
    """Run PageRank per ``cfg`` on the default device (single-chip path;
    the sharded multi-chip path is parallel/pagerank_sharded.py)."""
    config.ensure_dtype_support(cfg.dtype)
    metrics = metrics or MetricsRecorder()
    n = graph.n_nodes
    if n == 0:
        return PageRankResult(np.zeros(0, cfg.dtype), 0, 0.0, metrics)
    cfg = driver.resolve_personalize(graph, cfg)

    # The one-time host layout build (degree sort / head split / bucket
    # padding for the hybrid and sort_shuffle impls) is amortized over the
    # whole run — record it so bench.py can prove that claim.
    with Timer() as t_put:
        dg = put_graph_for(graph, cfg)
    metrics.record(event="put_graph", spmv_impl=cfg.spmv_impl,
                   preprocess_secs=t_put.elapsed)
    e = jax.device_put(ops.restart_vector(n, cfg))
    ranks = np.asarray(ops.init_ranks(n, cfg))
    start_iter = driver.resume_from_checkpoint(cfg, metrics, ranks, n=n) if resume else 0
    ranks_dev = jax.device_put(ranks.astype(cfg.dtype))

    make = ops.make_spark_exact_runner if cfg.spark_exact else ops.make_pagerank_runner

    def invoke(runner, rd):
        # Async dispatch consumes (donates) the rank carry ``rd`` — so the
        # scalar sync below must NOT surface transient failures to the
        # outer pagerank_step guard, whose retry would re-dispatch into
        # the consumed buffer.  The fetch gets its own guarded site: a
        # tunnel blip re-pulls the scalar against the still-live OUTPUT
        # buffers, which is always safe.
        rd, iters, delta = runner(dg, rd, e)
        with obs.span("pagerank.delta_sync"):
            delta = float(rx.device_get(
                delta, site="pagerank_delta_sync", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            ))  # scalar fetch is the only reliable device sync
        return rd, iters, delta

    def make_cpu_invoke(seg_cfg):
        """Degradation-ladder rung (resilience/executor.py): re-lower the
        segment for the CPU backend and run it there.  The graph is re-put
        from host state — the device copy may be gone with the device —
        and the live ranks are pulled through the guarded executor (the
        pull itself can hang on a dead tunnel)."""
        runner = make(n, seg_cfg)

        def cpu_invoke(rd):
            with obs.span("pagerank.cpu_degrade"):
                cpu = jax.devices("cpu")[0]
                with jax.default_device(cpu):
                    dg_cpu = put_graph_for(graph, cfg)
                    e_cpu = jax.device_put(
                        rx.device_get(e, site="pagerank_cpu_pull"), cpu
                    )
                    rd_cpu = jax.device_put(
                        rx.device_get(rd, site="pagerank_cpu_pull"), cpu
                    )
                    out, iters, delta = runner(dg_cpu, rd_cpu, e_cpu)
                    delta = float(delta)
            return out, iters, delta

        return cpu_invoke

    def extract_np(rd):
        with obs.span("pagerank.ckpt_pull"):
            return rx.device_get(
                rd, site="pagerank_ckpt_pull", metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir,
            )

    def init_state() -> np.ndarray:
        return np.asarray(ops.init_ranks(n, cfg))

    def cpu_exec(seg_cfg, ranks_g: np.ndarray):
        """Re-lower on the CPU backend from HOST state (graph re-put, no
        read of any dead device buffer) and run ``seg_cfg.iterations``."""
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            dg_cpu = put_graph_for(graph, cfg)
            e_cpu = jax.device_put(np.asarray(ops.restart_vector(n, cfg)), cpu)
            rd_cpu = jax.device_put(ranks_g.astype(cfg.dtype), cpu)
            runner = make(n, seg_cfg)
            rd2, iters, delta = runner(dg_cpu, rd_cpu, e_cpu)
            return rd2, int(iters), float(delta), dg_cpu, e_cpu

    def cpu_salvage_exec(rerun_cfg, ranks_g: np.ndarray):
        """dataflow.fixpoint.make_cpu_salvage contract: CPU re-lowering +
        rerun from host state, returning the replacement invoke."""
        rd2, iters, delta, dg_cpu, e_cpu = cpu_exec(rerun_cfg, ranks_g)

        def cpu_invoke2(runner, rd):
            rd, iters, delta = runner(dg_cpu, rd, e_cpu)
            with obs.span("pagerank.delta_sync"):
                delta = float(rx.device_get(
                    delta, site="pagerank_delta_sync", metrics=metrics,
                    checkpoint_dir=cfg.checkpoint_dir,
                ))
            return rd, iters, delta

        return rd2, iters, delta, cpu_invoke2

    # The single-chip elastic salvage rung (carried-forward ISSUE 9
    # satellite): a device-attributed loss first surfacing at the delta
    # sync, checkpoint pull or result pull used to dead-end — the CPU
    # rung re-*pulled* the dead/donated carry and failed with it.  The
    # rung is the SHARED dataflow one: salvage newest snapshot, rerun the
    # uncommitted span on the CPU backend, swap the loop onto CPU
    # execution.  Whole-backend faults keep the legacy cpu rung.
    elastic_salvage = dflow.make_cpu_salvage(
        cfg, metrics, site_prefix="pagerank",
        init_state=init_state, cpu_exec=cpu_salvage_exec,
        make_runner=lambda c: make(n, c), extract_np=extract_np,
    )

    ranks_dev, done, last_delta = driver.run_segments(
        cfg, metrics, ranks_dev, start_iter,
        make_runner=lambda seg_cfg: make(n, seg_cfg),
        invoke=invoke,
        extract_np=extract_np,
        segments_allowed=not cfg.spark_exact,
        make_cpu_invoke=make_cpu_invoke,
        elastic_rebuild=elastic_salvage,
    )

    with obs.span("pagerank.result_pull"):
        # Device loss first surfacing at the RESULT pull walks the same
        # shared salvage rung (checkpoint → CPU re-run of the uncommitted
        # span → pull from the CPU buffers).
        ranks_np = rx.device_get(
            ranks_dev, site="pagerank_result_pull", metrics=metrics,
            checkpoint_dir=cfg.checkpoint_dir,
            fallbacks=[(None, dflow.make_pull_salvage(
                cfg, metrics, site_prefix="pagerank",
                init_state=init_state, cpu_exec=cpu_salvage_exec,
                get_done=lambda: done,
            ))],
        )
    return PageRankResult(
        ranks=ranks_np, iterations=done, l1_delta=last_delta, metrics=metrics
    )
