"""Shared host-side helpers for the PageRank model drivers.

The segment loop itself — run the compiled iteration program in
checkpoint-sized segments with the resilience ladder attached — moved to
the dataflow core (``dataflow/fixpoint.py``: it is the host half of the
``fixpoint`` primitive, shared by PageRank and every new fixpoint
workload); :func:`run_segments` and :class:`ElasticResult` are re-exported
here unchanged for the existing call sites.  What remains native to this
module is PageRank-driver bookkeeping: personalize-id resolution and
checkpoint resume.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.fixpoint import (  # noqa: F401 — re-exported API
    ElasticResult,
    run_segments,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


def resolve_personalize(graph, cfg: PageRankConfig) -> PageRankConfig:
    """Map ``cfg.personalize`` from ORIGINAL node ids (what the user knows
    from the edge file) to compacted row indices (what restart_vector
    needs).  SNAP inputs have id gaps, so passing originals through
    unmapped would silently personalize the wrong nodes.  ``node_ids`` is
    sorted (np.unique), so the lookup is a searchsorted."""
    if cfg.personalize is None:
        return cfg
    ids = np.asarray(cfg.personalize, dtype=np.int64)
    pos = np.searchsorted(graph.node_ids, ids)
    ok = (pos < graph.n_nodes) & (graph.node_ids[np.minimum(pos, graph.n_nodes - 1)] == ids)
    if not ok.all():
        missing = ids[~ok].tolist()
        raise ValueError(f"personalize node ids not present in the graph: {missing}")
    return dataclasses.replace(cfg, personalize=tuple(int(p) for p in pos))


def resume_from_checkpoint(
    cfg: PageRankConfig, metrics: MetricsRecorder, ranks_np: np.ndarray, *, n: int
) -> int:
    """Load the latest checkpoint into ``ranks_np`` (in place, first ``n``
    rows — ``ranks_np`` may carry shard padding beyond the logical node
    count); returns the start iteration.

    Checkpoints always store exactly the logical ``n`` ranks, so a size
    mismatch means the checkpoint belongs to a different graph (the config
    hash can't catch that: it excludes the input) and must fail loudly
    rather than partially initialize.
    """
    if not cfg.checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    if latest is None:
        return 0
    start_iter, arrays, _ = ckpt.load_checkpoint(latest, cfg.config_hash())
    saved = arrays["ranks"]
    if saved.shape[0] != n:
        raise ValueError(
            f"checkpoint {latest} holds {saved.shape[0]} ranks but the graph "
            f"has {n} nodes; refusing to resume from a different graph"
        )
    ranks_np[:n] = saved
    metrics.record(event="resume", path=latest, start_iter=start_iter)
    return start_iter
