"""Shared host-side segment driver for the PageRank runners.

Both the single-chip (models/pagerank.py) and sharded
(parallel/pagerank_sharded.py) paths execute the same host loop: run the
compiled iteration program in segments, snapshot state between segments,
stop early on tolerance.  The loop lives here once so checkpoint/convergence
fixes cannot diverge between the two drivers.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, NamedTuple

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


def resolve_personalize(graph, cfg: PageRankConfig) -> PageRankConfig:
    """Map ``cfg.personalize`` from ORIGINAL node ids (what the user knows
    from the edge file) to compacted row indices (what restart_vector
    needs).  SNAP inputs have id gaps, so passing originals through
    unmapped would silently personalize the wrong nodes.  ``node_ids`` is
    sorted (np.unique), so the lookup is a searchsorted."""
    if cfg.personalize is None:
        return cfg
    ids = np.asarray(cfg.personalize, dtype=np.int64)
    pos = np.searchsorted(graph.node_ids, ids)
    ok = (pos < graph.n_nodes) & (graph.node_ids[np.minimum(pos, graph.n_nodes - 1)] == ids)
    if not ok.all():
        missing = ids[~ok].tolist()
        raise ValueError(f"personalize node ids not present in the graph: {missing}")
    return dataclasses.replace(cfg, personalize=tuple(int(p) for p in pos))


def resume_from_checkpoint(
    cfg: PageRankConfig, metrics: MetricsRecorder, ranks_np: np.ndarray, *, n: int
) -> int:
    """Load the latest checkpoint into ``ranks_np`` (in place, first ``n``
    rows — ``ranks_np`` may carry shard padding beyond the logical node
    count); returns the start iteration.

    Checkpoints always store exactly the logical ``n`` ranks, so a size
    mismatch means the checkpoint belongs to a different graph (the config
    hash can't catch that: it excludes the input) and must fail loudly
    rather than partially initialize.
    """
    if not cfg.checkpoint_dir:
        raise ValueError("resume=True requires checkpoint_dir")
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    if latest is None:
        return 0
    start_iter, arrays, _ = ckpt.load_checkpoint(latest, cfg.config_hash())
    saved = arrays["ranks"]
    if saved.shape[0] != n:
        raise ValueError(
            f"checkpoint {latest} holds {saved.shape[0]} ranks but the graph "
            f"has {n} nodes; refusing to resume from a different graph"
        )
    ranks_np[:n] = saved
    metrics.record(event="resume", path=latest, start_iter=start_iter)
    return start_iter


class ElasticResult(NamedTuple):
    """What an elastic shrink handler returns after it rebuilt the mesh
    and ran the failed segment on the survivors: the segment outputs plus
    the replacement callables every *subsequent* segment must use."""

    ranks_dev: object
    iters: int  # effective NEW iterations relative to the pre-failure count
    delta: float
    make_runner: Callable
    invoke: Callable
    extract_np: Callable
    metrics_extra: dict  # merged into per-segment metrics (e.g. devices=N)


def run_segments(
    cfg: PageRankConfig,
    metrics: MetricsRecorder,
    ranks_dev,
    start_iter: int,
    *,
    make_runner: Callable[[PageRankConfig], Callable],
    invoke: Callable,
    extract_np: Callable[[object], np.ndarray],
    segments_allowed: bool = True,
    extra_metrics: dict | None = None,
    make_cpu_invoke: Callable[[PageRankConfig], Callable] | None = None,
    elastic_rebuild: Callable | None = None,
):
    """Run ``cfg.iterations`` in checkpoint-sized compiled segments.

    - ``make_runner(seg_cfg)`` compiles the loop for one segment length;
      called at most twice (body segments + tail) thanks to caching here.
    - ``invoke(runner, ranks_dev)`` executes and returns
      ``(ranks_dev, iters_done, delta)`` with a completed host sync.
    - ``extract_np(ranks_dev)`` yields the checkpointable rank array.
    - ``make_cpu_invoke(seg_cfg)``, when given, builds the degradation-
      ladder rung: a ``ranks_dev -> (ranks_dev, iters, delta)`` callable
      re-lowered for the CPU backend, run when on-device retries are
      exhausted or the device is lost.
    - ``elastic_rebuild(exc, ranks_dev, done, seg_cfg)``, when given, is
      the mesh-shrink rung for sharded runners: on device loss it salvages
      the current state, rebuilds the mesh over the surviving devices,
      repartitions, runs the failed segment there, and returns an
      :class:`ElasticResult` whose callables replace this loop's (the
      runner cache is dropped — every compiled program was welded to the
      dead mesh).  It raises when it does not apply (not a device loss,
      elastic disabled, nothing survives), passing the ladder on.

    Each segment dispatch runs under the resilience executor: transient
    failures retry with backoff (the runner is functional, so re-invoking
    with the same ranks cannot double-apply iterations), persistent ones
    walk the rungs above, and exhaustion raises ``ResilienceExhausted``
    carrying the latest checkpoint under ``cfg.checkpoint_dir``.  The
    single-chip runners *donate* their rank carry (ops/pagerank.py), so
    ``invoke`` must never let a post-dispatch sync failure reach this
    site's retry (which would re-dispatch into the consumed buffer):
    models/pagerank.py fetches the delta through its own guarded site
    (``pagerank_delta_sync``) whose retries re-pull against live OUTPUT
    buffers, and an exhausted inner fetch is non-transient here — it
    walks the rungs, and a rung that cannot read the consumed carry
    raises onward until ``ResilienceExhausted`` hands the caller the
    latest checkpoint.  This site's own transient failures (chaos fires
    at attempt start, before dispatch) still retry with the carry
    intact.

    Checkpoints are tagged with the segment's ``extra_metrics`` (the
    sharded runners put ``devices=N`` there), so a snapshot records which
    mesh shape wrote it — while staying readable across shrinks, because
    the payload is always the logical ``n`` ranks.

    Returns ``(ranks_dev, done, last_delta)``.
    """
    segment = (
        cfg.checkpoint_every
        if (cfg.checkpoint_every > 0 and cfg.tol == 0.0 and segments_allowed)
        else cfg.iterations - start_iter
    )
    # GRAFT_SYNC_DEADLINE_S guards *host syncs*, whose healthy duration is
    # bounded; a compiled segment's legitimate runtime scales with its
    # iteration count, so inheriting the sync deadline here would kill
    # healthy long segments.  The dispatch site gets its own knob
    # (GRAFT_STEP_DEADLINE_S, default 0 = no watchdog).
    policy = dataclasses.replace(
        rx.RetryPolicy.from_env(),
        deadline_s=float(os.environ.get("GRAFT_STEP_DEADLINE_S", 0.0)),
    )
    runners: dict[int, Callable] = {}
    cpu_invokes: dict[int, Callable] = {}
    done = start_iter
    last_delta = float("inf")
    while done < cfg.iterations:
        todo = min(segment, cfg.iterations - done)
        seg_cfg = dataclasses.replace(
            cfg, iterations=todo, checkpoint_every=0, checkpoint_dir=None
        )
        if todo not in runners:
            runners[todo] = make_runner(seg_cfg)
        rungs: list = []
        if elastic_rebuild is not None:
            def elastic_rung(exc, seg_cfg=seg_cfg, rd=ranks_dev):
                # salvage + shrink + rerun happen in the handler; here we
                # only swap this loop onto the rebuilt execution context
                nonlocal make_runner, invoke, extract_np, extra_metrics
                res: ElasticResult = elastic_rebuild(exc, rd, done, seg_cfg)
                make_runner, invoke, extract_np = (
                    res.make_runner, res.invoke, res.extract_np
                )
                extra_metrics = {**(extra_metrics or {}), **res.metrics_extra}
                runners.clear()  # every cached program targeted the old mesh
                cpu_invokes.clear()
                return res.ranks_dev, res.iters, res.delta

            rungs.append((None, elastic_rung))
        if make_cpu_invoke is not None:
            def cpu_rung(_exc, todo=todo, seg_cfg=seg_cfg, rd=ranks_dev):
                if todo not in cpu_invokes:
                    cpu_invokes[todo] = make_cpu_invoke(seg_cfg)
                return cpu_invokes[todo](rd)

            rungs.append(("cpu", cpu_rung))
        with Timer() as t, obs.span("pagerank.segment", start=done, todo=todo):
            ranks_dev, iters, delta = rx.run_guarded(
                lambda r=runners[todo], rd=ranks_dev: invoke(r, rd),
                site="pagerank_step", policy=policy, metrics=metrics,
                checkpoint_dir=cfg.checkpoint_dir, fallbacks=rungs,
            )
        done += int(iters)
        last_delta = float(delta)
        obs.histogram("pagerank.segment_secs", t.elapsed)
        metrics.record(
            iter=done,
            l1_delta=last_delta,
            secs=t.elapsed,
            iters_per_sec=int(iters) / t.elapsed if t.elapsed > 0 else float("inf"),
            **(extra_metrics or {}),
        )
        if cfg.checkpoint_every > 0 and cfg.checkpoint_dir and done < cfg.iterations:
            with obs.span("pagerank.checkpoint", iter=done):
                path = ckpt.save_checkpoint(
                    cfg.checkpoint_dir, done,
                    {"ranks": extract_np(ranks_dev)}, cfg.config_hash(),
                    extra=dict(extra_metrics or {}),
                )
            metrics.record(event="checkpoint", path=path, iter=done)
        if cfg.tol > 0.0:
            # the while_loop runner handled tolerance in-program; one
            # segment is the whole run
            break

    metrics.scalar("iterations", done)
    metrics.scalar("l1_delta", last_delta)
    return ranks_dev, done, last_delta
