"""Host-side text ingest: corpus → tokens → hashed (doc_id, term_id) arrays.

Reference counterpart (SURVEY.md §2.1 A7, §3.2): Spark's
``wholeTextFiles(corpus).flatMap(tokenize)`` emitting ``((term, doc), 1)``
records into a shuffle.  TPU-native design: tokenize on host, hash every
token with a stable 64-bit FNV-1a into a ``2**vocab_bits`` id space
(BASELINE.json:8: "unigram hashed vocab 2^18"), and ship flat int32
``(doc_id, term_id)`` arrays to the device where TF and DF are single
``segment_sum`` calls.

The hash is implemented twice with identical results: a vectorized numpy
column-sweep here (fast enough for tests and 20-Newsgroups scale) and a C++
kernel in ``native/fastio.cpp`` for Wikipedia-scale streaming ingest —
``tests/test_native.py`` pins them equal.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Iterable, Iterator, Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9]+")

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def tokenize(text: str, *, lowercase: bool = True, min_token_len: int = 1) -> list[str]:
    """Split on non-alphanumerics (the canonical course-project tokenizer —
    SURVEY.md A7), optionally lowercasing and dropping short tokens."""
    if lowercase:
        text = text.lower()
    toks = _TOKEN_RE.findall(text)
    if min_token_len > 1:
        toks = [t for t in toks if len(t) >= min_token_len]
    return toks


def add_ngrams(tokens: Sequence[str], n: int) -> list[str]:
    """Extend a unigram stream with joined n-grams up to ``n`` (n=2 matches
    BASELINE.json:11's "bigram vocab": unigrams + space-joined bigrams)."""
    out = list(tokens)
    for k in range(2, n + 1):
        out.extend(" ".join(tokens[i : i + k]) for i in range(len(tokens) - k + 1))
    return out


def fnv1a_64(tokens: Sequence[str]) -> np.ndarray:
    """Stable 64-bit FNV-1a of each token's UTF-8 bytes, vectorized.

    Tokens are right-padded into a uint8 matrix and hashed with one numpy
    sweep per byte column, masked past each token's length — no per-token
    python loop.
    """
    if len(tokens) == 0:
        return np.empty(0, dtype=np.uint64)
    bts = [t.encode("utf-8") for t in tokens]
    lens = np.fromiter((len(b) for b in bts), dtype=np.int64, count=len(bts))
    width = max(1, int(lens.max()))
    mat = np.zeros((len(bts), width), dtype=np.uint8)
    joined = np.frombuffer(b"".join(bts), dtype=np.uint8)
    # Scatter the concatenated bytes into the padded matrix rows.
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
    col = np.arange(width)
    idx = starts[:, None] + col[None, :]
    valid = col[None, :] < lens[:, None]
    mat[valid] = joined[idx[valid]]

    h = np.full(len(bts), _FNV_OFFSET, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for c in range(width):
            m = valid[:, c]
            h[m] = (h[m] ^ mat[:, c][m].astype(np.uint64)) * _FNV_PRIME
    return h


def hash_to_vocab(hashes: np.ndarray, vocab_bits: int) -> np.ndarray:
    """Fold 64-bit hashes into ``[0, 2**vocab_bits)`` (mask — power-of-two
    vocab, BASELINE.json:8)."""
    mask = np.uint64((1 << vocab_bits) - 1)
    return (hashes & mask).astype(np.int32)


@dataclasses.dataclass(frozen=True)
class TokenizedCorpus:
    """Flat device-ready token stream for a batch of documents.

    ``doc_ids[t]`` / ``term_ids[t]`` give document index and hashed vocab id
    of token occurrence ``t``; ``doc_lengths[d]`` counts tokens of doc ``d``
    (for TF normalization).  ``doc_names`` maps doc index → source name.
    """

    n_docs: int
    vocab_bits: int
    doc_ids: np.ndarray  # int32 [n_tokens]
    term_ids: np.ndarray  # int32 [n_tokens]
    doc_lengths: np.ndarray  # int32 [n_docs]
    doc_names: tuple[str, ...]

    @property
    def n_tokens(self) -> int:
        return int(self.doc_ids.shape[0])


def tokenize_corpus(
    docs: Sequence[str],
    *,
    vocab_bits: int = 18,
    ngram: int = 1,
    lowercase: bool = True,
    min_token_len: int = 1,
    doc_names: Sequence[str] | None = None,
    doc_id_offset: int = 0,
) -> TokenizedCorpus:
    """Tokenize + hash a batch of document strings.

    Uses the native C++ tokenizer+hasher when available (SURVEY.md §7 flags
    the host tokenizer as the Wikipedia-scale bottleneck), falling back to
    the numpy FNV sweep.  ``doc_id_offset`` lets streaming ingest assign
    globally unique doc ids chunk by chunk.

    Each call is an ``io.tokenize`` span: the tokenizer is the documented
    Wikipedia-scale bottleneck, so its exact share of a traced run (vs
    padding/dispatch/drain) must be separable in the timeline — including
    when it runs on the streaming prefetch thread.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu import obs
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

    with obs.span("io.tokenize", docs=len(docs)):
        res = native.tokenize_and_hash(
            docs,
            vocab_bits=vocab_bits,
            ngram=ngram,
            lowercase=lowercase,
            min_token_len=min_token_len,
        )
        if res is not None:
            doc_ids, term_ids, doc_lengths = res
        else:
            per_doc: list[list[str]] = [
                add_ngrams(tokenize(d, lowercase=lowercase, min_token_len=min_token_len), ngram)
                for d in docs
            ]
            doc_lengths = np.fromiter((len(p) for p in per_doc), dtype=np.int32, count=len(per_doc))
            flat = [t for p in per_doc for t in p]
            term_ids = hash_to_vocab(fnv1a_64(flat), vocab_bits)
            doc_ids = np.repeat(np.arange(len(docs), dtype=np.int32), doc_lengths)

    names = tuple(doc_names) if doc_names is not None else tuple(
        f"doc{doc_id_offset + i}" for i in range(len(docs))
    )
    return TokenizedCorpus(
        n_docs=len(docs),
        vocab_bits=vocab_bits,
        doc_ids=doc_ids + np.int32(doc_id_offset),
        term_ids=term_ids,
        doc_lengths=doc_lengths,
        doc_names=names,
    )


def load_corpus_dir(path: str) -> tuple[list[str], list[str]]:
    """Directory of text files → (docs, names); one document per file —
    the reference's ``wholeTextFiles`` (SURVEY.md §3.2)."""
    names, docs = [], []
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if os.path.isfile(full):
            with open(full, "r", errors="replace") as f:
                docs.append(f.read())
            names.append(name)
    return docs, names


def load_corpus_lines(path: str) -> tuple[list[str], list[str]]:
    """One document per line (the usual flat-file corpus dump shape)."""
    with open(path, "r", errors="replace") as f:
        docs = f.read().splitlines()
    return docs, [f"line{i}" for i in range(len(docs))]


def iter_corpus_lines(path: str) -> Iterator[str]:
    """Lazy one-doc-per-line reader: streaming ingest must not materialize
    the whole corpus on host (the Wikipedia config, BASELINE.json:11)."""
    with open(path, "r", errors="replace") as f:
        for line in f:
            yield line.rstrip("\n")


def iter_corpus_dir(path: str) -> Iterator[str]:
    """Lazy directory reader (one doc per file), same contract as above."""
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if os.path.isfile(full):
            with open(full, "r", errors="replace") as f:
                yield f.read()


def iter_corpus_chunks(
    docs: Iterable[str],
    chunk_docs: int,
    *,
    skip_chunks: int = 0,
    expect_skipped_docs: int | None = None,
) -> Iterator[list[str]]:
    """Fixed-size document chunks for streaming ingest (BASELINE.json:11).

    ``skip_chunks``: the resumable-streaming fast path.  A resuming
    consumer (models.tfidf ``resume=True``) ignores the first
    ``resume_point(cfg)`` chunks by *index*, so for those chunks this
    iterator yields an empty placeholder instead of buffering their
    documents — chunk indices (and therefore checkpoint bookkeeping) stay
    stable while the ingested prefix is never materialized on host.

    ``expect_skipped_docs``: the checkpoint's ingested document count.
    Chunk indices only line up if the corpus is re-chunked identically, so
    when given, the skipped prefix must cover exactly this many documents
    — a different ``chunk_docs`` between runs fails loudly here instead of
    silently re-ingesting (or dropping) documents.
    """
    buf: list[str] = []
    pending = 0  # docs counted through the current skipped chunk
    skipped_docs = 0
    emitted = 0
    for d in docs:
        if emitted < skip_chunks:
            pending += 1
            skipped_docs += 1
            if pending == chunk_docs:
                yield []  # placeholder: keeps downstream chunk indices stable
                pending = 0
                emitted += 1
                if emitted == skip_chunks and (
                    expect_skipped_docs is not None
                    and skipped_docs != expect_skipped_docs
                ):
                    raise ValueError(
                        f"resume chunking mismatch: skipping {skip_chunks} "
                        f"chunk(s) of {chunk_docs} covers {skipped_docs} "
                        f"documents but the checkpoint ingested "
                        f"{expect_skipped_docs}; rerun with the original "
                        "--chunk-docs"
                    )
            continue
        buf.append(d)
        if len(buf) == chunk_docs:
            yield buf
            buf = []
            emitted += 1
    # The corpus may legitimately end inside the skipped prefix when the
    # checkpoint covers a partial final chunk (e.g. a crash after ingest,
    # during finalize) — only a document-count mismatch is an error.
    if (
        emitted < skip_chunks
        and expect_skipped_docs is not None
        and skipped_docs != expect_skipped_docs
    ):
        raise ValueError(
            f"resume chunking mismatch: the corpus ended after "
            f"{skipped_docs} documents, inside the {skip_chunks}-chunk "
            f"skipped prefix (checkpoint ingested {expect_skipped_docs}); "
            "the corpus or --chunk-docs changed since the checkpoint"
        )
    if buf or pending:
        yield buf
