"""Host-side graph ingest: SNAP edge lists → device-ready edge arrays.

Reference counterpart (SURVEY.md §2.1 A2/A3): the Spark chain
``sc.textFile(edges).map(parse).distinct().groupByKey().cache()`` — a text
parse followed by a dedup shuffle and an adjacency-list build kept hot
across iterations.  TPU-native design: parse once on host into flat numpy
arrays, dedup with one vectorized sort, and keep the graph device-resident
as **destination-sorted edge arrays** (a CSC-by-destination layout): the
per-iteration `reduceByKey` then becomes a `segment_sum` over contiguous
destination segments, which is the layout XLA tiles best.

SNAP format: ``#``-prefixed comment header lines, whitespace-separated
integer ``src dst`` pairs (BASELINE.json:7,9 name SNAP web-Google and
soc-LiveJournal1).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class Graph:
    """A directed graph in destination-sorted edge-array form.

    Node ids are compacted to ``[0, n_nodes)``; ``node_ids[i]`` maps row
    ``i`` back to the original id from the input file (identity when the
    input was already compact).

    Invariants: ``dst`` is non-decreasing; ``(src, dst)`` pairs are unique
    (the reference's ``distinct()``); ``out_degree[v] == #edges with
    src == v``; dangling nodes are exactly ``out_degree == 0``.
    """

    n_nodes: int
    src: np.ndarray  # int32 [n_edges], sorted by (dst, src)
    dst: np.ndarray  # int32 [n_edges], non-decreasing
    out_degree: np.ndarray  # int32 [n_nodes]
    node_ids: np.ndarray  # original ids, [n_nodes]

    @property
    def n_edges(self) -> int:
        return int(self.src.shape[0])

    @property
    def dangling_mask(self) -> np.ndarray:
        return self.out_degree == 0

    def csr_indptr(self) -> np.ndarray:
        """int64 [n_nodes+1] CSR row pointers into the dst-sorted edge array
        (cached: every consumer — device graph build, shard partitioning,
        Pallas window metadata — shares one host pass)."""
        cached = getattr(self, "_indptr", None)
        if cached is None:
            cached = np.searchsorted(self.dst, np.arange(self.n_nodes + 1)).astype(np.int64)
            object.__setattr__(self, "_indptr", cached)
        return cached

    def __repr__(self) -> str:  # keep pytest output readable
        return f"Graph(n_nodes={self.n_nodes}, n_edges={self.n_edges})"


def from_edges(
    src: np.ndarray,
    dst: np.ndarray,
    *,
    dedup: bool = True,
    drop_self_loops: bool = False,
    compact_ids: bool = True,
) -> Graph:
    """Build a :class:`Graph` from raw (src, dst) id arrays.

    ``dedup=True`` reproduces the reference's ``distinct()``; self-loops are
    kept by default (``distinct()`` does not remove them).
    """
    src = np.asarray(src).ravel()
    dst = np.asarray(dst).ravel()
    if src.shape != dst.shape:
        raise ValueError(f"src/dst shape mismatch: {src.shape} vs {dst.shape}")
    if drop_self_loops:
        keep = src != dst
        src, dst = src[keep], dst[keep]

    if compact_ids:
        node_ids, inverse = np.unique(np.concatenate([src, dst]), return_inverse=True)
        src = inverse[: src.shape[0]]
        dst = inverse[src.shape[0] :]
        n = int(node_ids.shape[0])
    else:
        n = int(max(src.max(initial=-1), dst.max(initial=-1)) + 1) if src.size else 0
        if n > (1 << 31):
            raise ValueError(
                f"compact_ids=False with max id {n - 1}: the O(n) rank/degree "
                "vectors would not fit; use compact_ids=True"
            )
        node_ids = np.arange(n, dtype=np.int64)

    src = src.astype(np.int64)
    dst = dst.astype(np.int64)
    # Sort (dst major, src minor) — both the dedup order and the final
    # destination-sorted layout every SpMV impl relies on.  The native C++
    # radix sort wins by several x at soc-LiveJournal1 scale; the numpy
    # lexsort fallback is bit-identical (unlike a dst*n+src composite key,
    # neither can overflow for large raw ids under compact_ids=False).
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

    sorted_pair = (
        native.sort_dedup_edges(src, dst, dedup=dedup)
        if src.size and n <= (1 << 31) else None
    )
    if sorted_pair is not None:
        src, dst = sorted_pair
    else:
        order = np.lexsort((src, dst))
        src, dst = src[order], dst[order]
        if dedup and src.size:
            keep = np.empty(src.shape, dtype=bool)
            keep[0] = True
            keep[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
            src, dst = src[keep], dst[keep]

    out_degree = np.bincount(src, minlength=n).astype(np.int32)
    return Graph(
        n_nodes=n,
        src=src.astype(np.int32),
        dst=dst.astype(np.int32),
        out_degree=out_degree,
        node_ids=node_ids,
    )


def parse_snap_text(text: str | bytes, **kwargs) -> Graph:
    """Parse SNAP edge-list text (``#`` comments, whitespace-separated int
    pairs). Vectorized: one pass to strip comments, one ``split`` for all
    tokens."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="replace")
    data_lines = [ln for ln in text.splitlines() if ln and not ln.lstrip().startswith("#")]
    if not data_lines:
        return from_edges(np.empty(0, np.int64), np.empty(0, np.int64), **kwargs)
    flat = " ".join(data_lines).split()
    arr = np.array(flat, dtype=np.int64)
    if arr.size % 2 != 0:
        raise ValueError(f"edge list has odd token count {arr.size}; not (src, dst) pairs")
    pairs = arr.reshape(-1, 2)
    return from_edges(pairs[:, 0], pairs[:, 1], **kwargs)


def load_snap(path: str, **kwargs) -> Graph:
    """Load a SNAP-format edge-list file.

    Uses the native C++ parser (utils/native.py) when available — the pure
    python tokenize of a 69M-edge soc-LiveJournal1 file is the kind of host
    bottleneck SURVEY.md §7 flags — falling back to the numpy path.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

    pairs = native.parse_edge_file(path)
    if pairs is not None:
        return from_edges(pairs[:, 0], pairs[:, 1], **kwargs)
    with open(path, "rb") as f:
        return parse_snap_text(f.read(), **kwargs)


def save_ranks(path: str, graph: Graph, ranks: np.ndarray, *, top_k: int | None = None) -> None:
    """Write ``<original_node_id>\\t<rank>`` lines, highest rank first —
    the reference's ``saveAsTextFile`` of collected ranks (SURVEY.md A5)."""
    order = np.argsort(-ranks, kind="stable")
    if top_k is not None:
        order = order[:top_k]
    with open(path, "w") as f:
        for i in order:
            f.write(f"{graph.node_ids[i]}\t{ranks[i]:.10g}\n")


def synthetic_powerlaw(
    n_nodes: int,
    n_edges: int,
    *,
    seed: int = 0,
    zipf_a: float = 1.5,
) -> Graph:
    """Synthetic graph with a power-law in-degree distribution.

    Stand-in for the SNAP datasets (not mounted in this environment —
    BASELINE.md); matches their shape class: heavy-tailed degrees, dangling
    nodes, duplicate edges before dedup.  Sources uniform, destinations
    Zipf-distributed over a random permutation so "celebrity" nodes exist —
    the load-imbalance stressor SURVEY.md §7 calls out for sharded SpMV.
    """
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, size=n_edges, dtype=np.int64)
    # Zipf over ranks, clipped to [0, n_nodes), then scattered via a random
    # permutation so hot nodes are not all small ids.
    z = rng.zipf(zipf_a, size=n_edges) - 1
    z = np.minimum(z, n_nodes - 1)
    perm = rng.permutation(n_nodes)
    dst = perm[z]
    return from_edges(src, dst)
