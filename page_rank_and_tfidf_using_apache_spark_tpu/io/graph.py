"""Host-side graph ingest: SNAP edge lists → device-ready edge arrays.

Reference counterpart (SURVEY.md §2.1 A2/A3): the Spark chain
``sc.textFile(edges).map(parse).distinct().groupByKey().cache()`` — a text
parse followed by a dedup shuffle and an adjacency-list build kept hot
across iterations.  TPU-native design: parse once on host into flat numpy
arrays, dedup with one vectorized sort, and keep the graph device-resident
as **destination-sorted edge arrays** (a CSC-by-destination layout): the
per-iteration `reduceByKey` then becomes a `segment_sum` over contiguous
destination segments, which is the layout XLA tiles best.

SNAP format: ``#``-prefixed comment header lines, whitespace-separated
integer ``src dst`` pairs (BASELINE.json:7,9 name SNAP web-Google and
soc-LiveJournal1).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class Graph:
    """A directed graph in destination-sorted edge-array form.

    Node ids are compacted to ``[0, n_nodes)``; ``node_ids[i]`` maps row
    ``i`` back to the original id from the input file (identity when the
    input was already compact).

    Invariants: ``dst`` is non-decreasing; ``(src, dst)`` pairs are unique
    (the reference's ``distinct()``); ``out_degree[v] == #edges with
    src == v``; dangling nodes are exactly ``out_degree == 0``.
    """

    n_nodes: int
    src: np.ndarray  # int32 [n_edges], sorted by (dst, src)
    dst: np.ndarray  # int32 [n_edges], non-decreasing
    out_degree: np.ndarray  # int32 [n_nodes]
    node_ids: np.ndarray  # original ids, [n_nodes]
    # Optional per-edge weights aligned with src/dst (same (dst, src)
    # order).  None = unweighted.  Weights are strictly positive (enforced
    # by from_edges): a node's dangling status then stays "no out-edges"
    # under both conventions, and the weighted out-STRENGTH normalizer
    # (networkx ``pagerank(weight=)`` semantics) is always finite.
    weight: np.ndarray | None = None

    @property
    def n_edges(self) -> int:
        return int(self.src.shape[0])

    def out_strength(self) -> np.ndarray:
        """float64 [n_nodes] sum of outgoing edge weights (== out_degree
        for an unweighted graph); the normalizer of the weighted SpMV.
        Cached like csr_indptr."""
        cached = getattr(self, "_out_strength", None)
        if cached is None:
            if self.weight is None:
                cached = self.out_degree.astype(np.float64)  # graftlint: disable=dtype-drift (host-side normalizer staging; cast to the run dtype at put_graph)
            else:
                cached = np.bincount(
                    self.src, weights=self.weight, minlength=self.n_nodes
                )
            object.__setattr__(self, "_out_strength", cached)
        return cached

    def inv_out_strength(self, dtype) -> np.ndarray:
        """``1 / out_strength`` (0 at dangling nodes), divided in float64
        and cast to ``dtype`` AFTER — THE one implementation every graph
        consumer shares (put_graph, partition_graph, build_owned_shard):
        the 1e-9 f64 chip-count-invariance pins depend on all of them
        normalizing bit-identically."""
        s = self.out_strength()
        with np.errstate(divide="ignore"):
            return np.where(
                s > 0, 1.0 / np.where(s > 0, s, 1.0), 0.0
            ).astype(dtype)

    @property
    def dangling_mask(self) -> np.ndarray:
        return self.out_degree == 0

    def csr_indptr(self) -> np.ndarray:
        """int64 [n_nodes+1] CSR row pointers into the dst-sorted edge array
        (cached: every consumer — device graph build, shard partitioning,
        Pallas window metadata — shares one host pass)."""
        cached = getattr(self, "_indptr", None)
        if cached is None:
            cached = np.searchsorted(self.dst, np.arange(self.n_nodes + 1)).astype(np.int64)
            object.__setattr__(self, "_indptr", cached)
        return cached

    def __repr__(self) -> str:  # keep pytest output readable
        return f"Graph(n_nodes={self.n_nodes}, n_edges={self.n_edges})"


def from_edges(
    src: np.ndarray,
    dst: np.ndarray,
    *,
    weight: np.ndarray | None = None,
    dedup: bool = True,
    drop_self_loops: bool = False,
    compact_ids: bool = True,
) -> Graph:
    """Build a :class:`Graph` from raw (src, dst) id arrays.

    ``dedup=True`` reproduces the reference's ``distinct()``; self-loops are
    kept by default (``distinct()`` does not remove them).  ``weight`` (all
    entries > 0) rides along per edge; duplicate (src, dst) pairs SUM their
    weights under dedup (the parallel-edge collapse networkx applies when a
    multigraph is read as a weighted digraph).
    """
    src = np.asarray(src).ravel()
    dst = np.asarray(dst).ravel()
    if src.shape != dst.shape:
        raise ValueError(f"src/dst shape mismatch: {src.shape} vs {dst.shape}")
    if weight is not None:
        weight = np.asarray(weight, np.float64).ravel()  # graftlint: disable=dtype-drift (host-side edge weights; cast to the run dtype at put_graph/partition_graph)
        if weight.shape != src.shape:
            raise ValueError(
                f"weight shape {weight.shape} != edge shape {src.shape}"
            )
        if weight.size and not (weight > 0).all():
            raise ValueError("edge weights must be strictly positive")
    if drop_self_loops:
        keep = src != dst
        src, dst = src[keep], dst[keep]
        if weight is not None:
            weight = weight[keep]

    if compact_ids:
        node_ids, inverse = np.unique(np.concatenate([src, dst]), return_inverse=True)
        src = inverse[: src.shape[0]]
        dst = inverse[src.shape[0] :]
        n = int(node_ids.shape[0])
    else:
        n = int(max(src.max(initial=-1), dst.max(initial=-1)) + 1) if src.size else 0
        if n > (1 << 31):
            raise ValueError(
                f"compact_ids=False with max id {n - 1}: the O(n) rank/degree "
                "vectors would not fit; use compact_ids=True"
            )
        node_ids = np.arange(n, dtype=np.int64)

    src = src.astype(np.int64)
    dst = dst.astype(np.int64)
    # Sort (dst major, src minor) — both the dedup order and the final
    # destination-sorted layout every SpMV impl relies on.  The native C++
    # radix sort wins by several x at soc-LiveJournal1 scale; the numpy
    # lexsort fallback is bit-identical (unlike a dst*n+src composite key,
    # neither can overflow for large raw ids under compact_ids=False).
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

    sorted_pair = (
        native.sort_dedup_edges(src, dst, dedup=dedup)
        if src.size and n <= (1 << 31) and weight is None else None
    )
    if sorted_pair is not None:
        src, dst = sorted_pair
    else:
        order = np.lexsort((src, dst))
        src, dst = src[order], dst[order]
        if weight is not None:
            weight = weight[order]
        if dedup and src.size:
            keep = np.empty(src.shape, dtype=bool)
            keep[0] = True
            keep[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
            if weight is not None:
                # duplicate (src, dst) pairs collapse to one edge carrying
                # the SUM of their weights (groups are contiguous after the
                # lexsort, so one reduceat covers them all)
                weight = np.add.reduceat(weight, np.flatnonzero(keep))
            src, dst = src[keep], dst[keep]

    out_degree = np.bincount(src, minlength=n).astype(np.int32)
    return Graph(
        n_nodes=n,
        src=src.astype(np.int32),
        dst=dst.astype(np.int32),
        out_degree=out_degree,
        node_ids=node_ids,
        weight=weight,
    )


def parse_snap_text(text: str | bytes, **kwargs) -> Graph:
    """Parse SNAP edge-list text (``#`` comments, whitespace-separated int
    pairs). Vectorized: one pass to strip comments, one ``split`` for all
    tokens."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="replace")
    data_lines = [ln for ln in text.splitlines() if ln and not ln.lstrip().startswith("#")]
    if not data_lines:
        return from_edges(np.empty(0, np.int64), np.empty(0, np.int64), **kwargs)
    flat = " ".join(data_lines).split()
    arr = np.array(flat, dtype=np.int64)
    if arr.size % 2 != 0:
        raise ValueError(f"edge list has odd token count {arr.size}; not (src, dst) pairs")
    pairs = arr.reshape(-1, 2)
    return from_edges(pairs[:, 0], pairs[:, 1], **kwargs)


def load_snap(path: str, **kwargs) -> Graph:
    """Load a SNAP-format edge-list file.

    Uses the native C++ parser (utils/native.py) when available — the pure
    python tokenize of a 69M-edge soc-LiveJournal1 file is the kind of host
    bottleneck SURVEY.md §7 flags — falling back to the numpy path.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.utils import native

    pairs = native.parse_edge_file(path)
    if pairs is not None:
        return from_edges(pairs[:, 0], pairs[:, 1], **kwargs)
    with open(path, "rb") as f:
        return parse_snap_text(f.read(), **kwargs)


def save_ranks(path: str, graph: Graph, ranks: np.ndarray, *, top_k: int | None = None) -> None:
    """Write ``<original_node_id>\\t<rank>`` lines, highest rank first —
    the reference's ``saveAsTextFile`` of collected ranks (SURVEY.md A5)."""
    order = np.argsort(-ranks, kind="stable")
    if top_k is not None:
        order = order[:top_k]
    with open(path, "w") as f:
        for i in order:
            f.write(f"{graph.node_ids[i]}\t{ranks[i]:.10g}\n")


def synthetic_powerlaw(
    n_nodes: int,
    n_edges: int,
    *,
    seed: int = 0,
    zipf_a: float = 1.5,
) -> Graph:
    """Synthetic graph with a power-law in-degree distribution.

    Stand-in for the SNAP datasets (not mounted in this environment —
    BASELINE.md); matches their shape class: heavy-tailed degrees, dangling
    nodes, duplicate edges before dedup.  Sources uniform, destinations
    Zipf-distributed over a random permutation so "celebrity" nodes exist —
    the load-imbalance stressor SURVEY.md §7 calls out for sharded SpMV.
    """
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_nodes, size=n_edges, dtype=np.int64)
    # Zipf over ranks, clipped to [0, n_nodes), then scattered via a random
    # permutation so hot nodes are not all small ids.
    z = rng.zipf(zipf_a, size=n_edges) - 1
    z = np.minimum(z, n_nodes - 1)
    perm = rng.permutation(n_nodes)
    dst = perm[z]
    return from_edges(src, dst)


def synthetic_zipf(
    n_nodes: int,
    n_edges: int,
    *,
    seed: int = 0,
    exponent: float = 1.5,
    src_exponent: float | None = None,
) -> Graph:
    """Seeded Zipf graph hitting its TARGET counts exactly: exactly
    ``n_nodes`` nodes and exactly ``n_edges`` unique edges (ISSUE 15
    satellite; :func:`synthetic_powerlaw` only aims near them — dedup
    shrinks its edge count by a seed-dependent few percent, which makes
    cross-scale comparisons like the owned-strategy comm-bytes sweep
    noisy).  Destinations are Zipf(``exponent``) over a random
    permutation, so hub IN-degree follows the power law the sharded
    planners are stressed by; sources are uniform by default, or
    Zipf(``src_exponent``) over an independent permutation — the
    both-axes power law real web graphs have (SNAP web-Google's
    out-degree is as heavy-tailed as its in-degree), and the shape class
    under which the owned strategy's boundary is hub-dominated: distinct
    sources drawn from a Zipf(a) grow ~n^(1/a), so cut-crossing entries —
    and with them per-step comm bytes — are SUBLINEAR in node count (the
    MULTICHIP scale sweep measures exactly this exponent).

    Top-up rounds oversample until the deduped pool reaches the target,
    then a seeded uniform subsample trims to it — trimming uniformly
    preserves the degree distribution's shape.
    """
    if n_nodes < 2:
        raise ValueError(f"synthetic_zipf needs n_nodes >= 2, got {n_nodes}")
    if n_edges < 2:
        raise ValueError(f"synthetic_zipf needs n_edges >= 2, got {n_edges}")
    if n_edges > n_nodes * (n_nodes - 1):
        raise ValueError(
            f"target {n_edges} edges exceeds the simple-digraph capacity "
            f"of {n_nodes} nodes"
        )
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_nodes)
    perm_s = rng.permutation(n_nodes) if src_exponent is not None else None
    # Hub SOURCES (the top source ranks) link uniformly; only tail
    # sources link preferentially (Zipf destinations).  A directory hub
    # links broadly, a niche page links into the popular head — and
    # without the split, the (hub src × hub dst) pair mass makes i.i.d.
    # unique-edge sampling collide so hard the top-up loop crawls at 10x
    # scale (its distinct-pair capacity saturates).
    src_hub_ranks = 1024
    # Pin ids 0 and n_nodes-1 so the node COUNT is exact without id
    # compaction renumbering anything (dedup may drop the duplicates).
    keys = {np.int64(0) * n_nodes + (n_nodes - 1),
            np.int64(n_nodes - 1) * n_nodes + 0}
    pool = np.fromiter(keys, np.int64)
    accept = 1.0  # unique yield of the previous round, sizes the next
    while pool.size < n_edges:
        want = max(n_edges - pool.size, 1024)
        batch = int(min(want / max(accept, 0.05) * 1.25, 4 * n_edges)) + 64
        z = np.minimum(rng.zipf(exponent, size=batch) - 1, n_nodes - 1)
        dst = perm[z]
        if perm_s is None:
            src = rng.integers(0, n_nodes, size=batch, dtype=np.int64)
        else:
            zs = np.minimum(rng.zipf(src_exponent, size=batch) - 1,
                            n_nodes - 1)
            src = perm_s[zs]
            hub = zs < src_hub_ranks
            dst[hub] = rng.integers(0, n_nodes, size=int(hub.sum()),
                                    dtype=np.int64)
        before = pool.size
        pool = np.unique(np.concatenate([pool, src * n_nodes + dst]))
        accept = max((pool.size - before) / batch, 0.01)
    if pool.size > n_edges:
        # keep the two pinned endpoint edges; trim the rest uniformly
        pinned = np.isin(pool, np.fromiter(keys, np.int64))
        rest = np.flatnonzero(~pinned)
        take = rng.choice(rest, n_edges - int(pinned.sum()), replace=False)
        pool = np.concatenate([pool[pinned], pool[take]])
    src = pool // n_nodes
    dst = pool % n_nodes
    g = from_edges(src, dst, dedup=False, compact_ids=False)
    assert g.n_nodes == n_nodes and g.n_edges == n_edges
    return g
