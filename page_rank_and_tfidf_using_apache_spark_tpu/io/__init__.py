from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    Graph,
    from_edges,
    load_snap,
    parse_snap_text,
    save_ranks,
    synthetic_powerlaw,
    synthetic_zipf,
)
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
    TokenizedCorpus,
    iter_corpus_chunks,
    load_corpus_dir,
    load_corpus_lines,
    tokenize,
    tokenize_corpus,
)

__all__ = [
    "Graph",
    "from_edges",
    "load_snap",
    "parse_snap_text",
    "save_ranks",
    "synthetic_powerlaw",
    "synthetic_zipf",
    "TokenizedCorpus",
    "iter_corpus_chunks",
    "load_corpus_dir",
    "load_corpus_lines",
    "tokenize",
    "tokenize_corpus",
]
