"""Library API — the two capabilities of the reference behind two calls.

Reference counterpart: running ``spark-submit pagerank.py`` /
``spark-submit tfidf.py`` (SURVEY.md A1/A6); here the same surface as
importable functions, with the CLI drivers (cli/) as thin argv wrappers.
"""

from __future__ import annotations

from typing import Iterable, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import Graph
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import (
    PageRankResult,
    run_pagerank,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    TfidfOutput,
    run_tfidf,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    TfidfConfig,
)


def pagerank(
    graph: Graph, cfg: PageRankConfig | None = None, **kwargs
) -> PageRankResult:
    """Run PageRank on a :class:`Graph`.

    ``pagerank(g)`` reproduces the reference defaults: 20 iterations,
    damping 0.85, ranks initialized to 1.0, dangling mass dropped
    (BASELINE.json:7; SURVEY.md §3.1).  Keyword args construct/override the
    config: ``pagerank(g, iterations=50, dangling="redistribute")``.
    """
    if cfg is None:
        cfg = PageRankConfig(**kwargs)
    elif kwargs:
        import dataclasses

        cfg = dataclasses.replace(cfg, **kwargs)
    return run_pagerank(graph, cfg)


def tfidf(
    docs: Sequence[str] | Iterable[Sequence[str]],
    cfg: TfidfConfig | None = None,
    *,
    streaming: bool = False,
    **kwargs,
) -> TfidfOutput:
    """Compute TF-IDF over a corpus.

    ``docs`` is a sequence of document strings (batch) or, with
    ``streaming=True``, an iterable of document chunks (BASELINE.json:11).
    Defaults match the 20-Newsgroups config: unigrams, hashed vocab 2^18,
    raw TF, classic ``log(N/df)`` IDF (BASELINE.json:8; SURVEY.md §4).
    """
    if cfg is None:
        cfg = TfidfConfig(**kwargs)
    elif kwargs:
        import dataclasses

        cfg = dataclasses.replace(cfg, **kwargs)
    if streaming:
        return run_tfidf_streaming(docs, cfg)
    return run_tfidf(docs, cfg)
