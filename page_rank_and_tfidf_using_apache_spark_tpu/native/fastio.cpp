// Native host-side ingest kernels, bound via ctypes (utils/native.py).
//
// Reference counterpart: the JVM/native machinery Spark puts under its ingest
// path (SURVEY.md §2 native-code note).  The rebuild's device-side native
// layer is XLA; this file is the host-side native layer covering the two
// ingest loops SURVEY.md §7 flags as Python bottlenecks at scale:
//
//   1. SNAP edge-list parse (soc-LiveJournal1: 69M edges of text) — the
//      reference's `sc.textFile(edges).map(parse)` (SURVEY.md A2).
//   2. Tokenize + FNV-1a-hash (Wikipedia-scale streaming TF-IDF ingest) —
//      the reference's `flatMap(tokenize)` (SURVEY.md A7).
//
// Both must produce BIT-IDENTICAL output to the numpy fallbacks in
// io/graph.py and io/text.py; tests/test_native.py pins them equal.  Any
// input the numpy path would reject (non-integer edge tokens, odd token
// count) makes these return -1 so the caller falls back and surfaces the
// same Python-side error.
//
// Tokenizer semantics (must track io/text.py tokenize()): split on
// non-[A-Za-z0-9] bytes, optional ASCII lowercasing, drop tokens shorter
// than min_token_len.  Multi-byte UTF-8 sequences are all >= 0x80 so they
// act as separators in both implementations — with exactly two exceptions
// when lowercasing: the only Unicode codepoints whose Python str.lower()
// maps into ASCII are U+212A KELVIN SIGN (-> 'k', token continues) and
// U+0130 LATIN CAPITAL I WITH DOT (-> 'i' + combining U+0307, which ends
// the token after the 'i').  Both are handled below so Turkish/scientific
// text tokenizes identically on the fast path and the numpy fallback.

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// SNAP edge-list parsing
// ---------------------------------------------------------------------------

namespace {

inline bool is_ws(uint8_t c) {
  // Python str.split()/lstrip() whitespace, restricted to ASCII.
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

inline bool is_line_ws(uint8_t c) { return c == ' ' || c == '\t' || c == '\v' || c == '\f'; }

// Parse integer tokens from SNAP text.  When src/dst are non-null, fill
// them; always return the number of (src, dst) pairs, or -1 on any token
// the numpy path would reject (non-integer token, odd token count).
int64_t parse_edges_impl(const uint8_t* buf, int64_t n, int64_t* src,
                         int64_t* dst) {
  int64_t count = 0;  // integer tokens seen
  int64_t i = 0;
  while (i < n) {
    // Start of a line: skip leading blanks, then check for '#' comment.
    int64_t j = i;
    while (j < n && is_line_ws(buf[j])) j++;
    if (j < n && buf[j] == '#') {
      while (j < n && buf[j] != '\n') j++;
      i = j + 1;
      continue;
    }
    // Parse tokens until end of line.
    while (j < n && buf[j] != '\n') {
      if (is_ws(buf[j])) {
        j++;
        continue;
      }
      bool neg = false;
      if (buf[j] == '-') {
        neg = true;
        j++;
      }
      if (j >= n || buf[j] < '0' || buf[j] > '9') return -1;
      int64_t v = 0;
      while (j < n && buf[j] >= '0' && buf[j] <= '9') {
        int digit = buf[j] - '0';
        // int64 overflow: numpy's parse raises here, so bail to the
        // fallback instead of wrapping silently.
        if (v > (INT64_MAX - digit) / 10) return -1;
        v = v * 10 + digit;
        j++;
      }
      if (j < n && !is_ws(buf[j])) return -1;  // e.g. "12abc"
      if (neg) v = -v;
      if (src != nullptr) {
        if (count % 2 == 0) {
          src[count / 2] = v;
        } else {
          dst[count / 2] = v;
        }
      }
      count++;
    }
    i = j + 1;
  }
  if (count % 2 != 0) return -1;
  return count / 2;
}

}  // namespace

int64_t parse_edges_count(const uint8_t* buf, int64_t n) {
  return parse_edges_impl(buf, n, nullptr, nullptr);
}

int64_t parse_edges_fill(const uint8_t* buf, int64_t n, int64_t* src,
                         int64_t* dst) {
  return parse_edges_impl(buf, n, src, dst);
}

// ---------------------------------------------------------------------------
// Tokenize + FNV-1a hash
// ---------------------------------------------------------------------------

namespace {

constexpr uint64_t kFnvOffset = 14695981039346656037ULL;
constexpr uint64_t kFnvPrime = 1099511628211ULL;

inline bool is_alnum(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9');
}

inline uint8_t to_lower(uint8_t c) {
  return (c >= 'A' && c <= 'Z') ? c + ('a' - 'A') : c;
}

inline uint64_t fnv1a(const uint8_t* p, int64_t len, uint64_t h = kFnvOffset) {
  for (int64_t i = 0; i < len; i++) h = (h ^ p[i]) * kFnvPrime;
  return h;
}

struct TokenSpan {
  int64_t start;  // into the per-doc lowered scratch buffer
  int64_t len;
};

// Unicode whose Python str.lower() introduces ASCII (see header comment).
// Returns the lowered ASCII byte and consumed length, or 0 if p[i] does not
// start such a sequence.  `ends_token` is set for U+0130, whose lowercase
// trailing combining mark (U+0307) terminates the token in the regex path.
inline uint8_t special_lower(const uint8_t* p, int64_t len, int64_t i,
                             int64_t* consumed, bool* ends_token) {
  if (p[i] == 0xC4 && i + 1 < len && p[i + 1] == 0xB0) {  // U+0130
    *consumed = 2;
    *ends_token = true;
    return 'i';
  }
  if (p[i] == 0xE2 && i + 2 < len && p[i + 1] == 0x84 &&
      p[i + 2] == 0xAA) {  // U+212A KELVIN SIGN
    *consumed = 3;
    *ends_token = false;
    return 'k';
  }
  return 0;
}

// Tokenize one document (bytes [p, p+len)) into `scratch` + `spans`.
void tokenize_doc(const uint8_t* p, int64_t len, bool lowercase,
                  int64_t min_token_len, std::string* scratch,
                  std::vector<TokenSpan>* spans) {
  scratch->clear();
  spans->clear();
  int64_t i = 0;
  int64_t tok_start = -1;  // offset into scratch, -1 = not inside a token
  auto end_token = [&]() {
    if (tok_start >= 0) {
      int64_t tlen = static_cast<int64_t>(scratch->size()) - tok_start;
      if (tlen >= min_token_len) {
        spans->push_back(TokenSpan{tok_start, tlen});
      } else {
        scratch->resize(tok_start);
      }
      tok_start = -1;
    }
  };
  while (i < len) {
    int64_t consumed;
    bool ends_token;
    uint8_t lowered;
    if (is_alnum(p[i])) {
      if (tok_start < 0) tok_start = static_cast<int64_t>(scratch->size());
      scratch->push_back(
          static_cast<char>(lowercase ? to_lower(p[i]) : p[i]));
      i++;
    } else if (lowercase &&
               (lowered = special_lower(p, len, i, &consumed, &ends_token))) {
      if (tok_start < 0) tok_start = static_cast<int64_t>(scratch->size());
      scratch->push_back(static_cast<char>(lowered));
      if (ends_token) end_token();
      i += consumed;
    } else {
      end_token();
      i++;
    }
  }
  end_token();
}

// Number of emitted terms for m unigrams with n-grams up to `ngram`
// (matches io/text.py add_ngrams: unigrams, then 2-grams, ... n-grams).
inline int64_t term_count(int64_t m, int64_t ngram) {
  int64_t total = m;
  for (int64_t k = 2; k <= ngram; k++) {
    if (m - k + 1 > 0) total += m - k + 1;
  }
  return total;
}

}  // namespace

// Count total emitted terms across all docs.  `blob` is the concatenation
// of the docs' UTF-8 bytes; `doc_lens[d]` is doc d's byte length.
int64_t tokenize_hash_count(const uint8_t* blob, int64_t blob_len,
                            const int64_t* doc_lens, int64_t n_docs,
                            int64_t ngram, int64_t lowercase,
                            int64_t min_token_len) {
  (void)blob_len;
  std::string scratch;
  std::vector<TokenSpan> spans;
  int64_t total = 0;
  int64_t off = 0;
  for (int64_t d = 0; d < n_docs; d++) {
    tokenize_doc(blob + off, doc_lens[d], lowercase != 0, min_token_len,
                 &scratch, &spans);
    total += term_count(static_cast<int64_t>(spans.size()), ngram);
    off += doc_lens[d];
  }
  return total;
}

// Fill doc_ids/term_ids (int32 [total]) and doc_lengths (int32 [n_docs]).
// Emission order per doc matches add_ngrams: all unigrams in text order,
// then all 2-grams, then 3-grams, ...  n-gram hashes cover the bytes of
// the space-joined lowered tokens, identically to hashing the joined
// Python string.  Returns total terms written, or -1 on overflow vs the
// caller-allocated capacity implied by tokenize_hash_count.
int64_t tokenize_hash_fill(const uint8_t* blob, int64_t blob_len,
                           const int64_t* doc_lens, int64_t n_docs,
                           int64_t ngram, int64_t lowercase,
                           int64_t min_token_len, int64_t vocab_bits,
                           int32_t* doc_ids, int32_t* term_ids,
                           int32_t* doc_lengths) {
  (void)blob_len;
  const uint64_t mask = (vocab_bits >= 64)
                            ? ~0ULL
                            : ((1ULL << vocab_bits) - 1ULL);
  std::string scratch;
  std::vector<TokenSpan> spans;
  int64_t out = 0;
  int64_t off = 0;
  for (int64_t d = 0; d < n_docs; d++) {
    tokenize_doc(blob + off, doc_lens[d], lowercase != 0, min_token_len,
                 &scratch, &spans);
    const uint8_t* sp = reinterpret_cast<const uint8_t*>(scratch.data());
    const int64_t m = static_cast<int64_t>(spans.size());
    doc_lengths[d] = static_cast<int32_t>(term_count(m, ngram));
    // Unigrams.
    for (int64_t t = 0; t < m; t++) {
      uint64_t h = fnv1a(sp + spans[t].start, spans[t].len);
      doc_ids[out] = static_cast<int32_t>(d);
      term_ids[out] = static_cast<int32_t>(h & mask);
      out++;
    }
    // k-grams, k = 2..ngram: hash tok[i] ' ' tok[i+1] ' ' ... tok[i+k-1].
    for (int64_t k = 2; k <= ngram; k++) {
      for (int64_t t = 0; t + k <= m; t++) {
        uint64_t h = kFnvOffset;
        for (int64_t g = 0; g < k; g++) {
          if (g > 0) h = (h ^ static_cast<uint8_t>(' ')) * kFnvPrime;
          h = fnv1a(sp + spans[t + g].start, spans[t + g].len, h);
        }
        doc_ids[out] = static_cast<int32_t>(d);
        term_ids[out] = static_cast<int32_t>(h & mask);
        out++;
      }
    }
    off += doc_lens[d];
  }
  return out;
}

// ---------------------------------------------------------------------------
// Graph build: (dst, src) radix sort + dedup
// ---------------------------------------------------------------------------

// The graph-builder's hot step (io/graph.py from_edges): order compacted
// edges by (dst major, src minor) and drop duplicates — the reference's
// `distinct()` + the dst-sorted layout every SpMV impl relies on.  numpy's
// lexsort is a comparison sort; at soc-LiveJournal1 scale (69M edges,
// SURVEY.md §6 config 3) an LSD radix sort over the packed (dst<<32)|src
// key is several times faster.  Requires compacted ids < 2^31 (guaranteed
// by from_edges before calling).  Sorts in place; returns the deduped edge
// count, or -1 on invalid input (id out of range).
int64_t sort_dedup_edges(int64_t* src, int64_t* dst, int64_t e, int64_t dedup) {
  if (e <= 0) return e < 0 ? -1 : 0;
  constexpr int64_t kMaxId = (int64_t{1} << 31) - 1;
  std::vector<uint64_t> keys(static_cast<size_t>(e));
  for (int64_t i = 0; i < e; i++) {
    if (src[i] < 0 || src[i] > kMaxId || dst[i] < 0 || dst[i] > kMaxId) return -1;
    keys[static_cast<size_t>(i)] =
        (static_cast<uint64_t>(dst[i]) << 32) | static_cast<uint64_t>(src[i]);
  }
  // LSD radix, 16-bit digits, 4 passes.
  std::vector<uint64_t> tmp(static_cast<size_t>(e));
  std::vector<int64_t> counts(1 << 16);
  uint64_t* cur = keys.data();
  uint64_t* alt = tmp.data();
  for (int pass = 0; pass < 4; pass++) {
    const int shift = pass * 16;
    std::memset(counts.data(), 0, counts.size() * sizeof(int64_t));
    for (int64_t i = 0; i < e; i++) counts[(cur[i] >> shift) & 0xFFFF]++;
    if (counts[0] == e) continue;  // digit constant (common for high bits)
    int64_t total = 0;
    for (int64_t& c : counts) {
      int64_t was = c;
      c = total;
      total += was;
    }
    for (int64_t i = 0; i < e; i++) alt[counts[(cur[i] >> shift) & 0xFFFF]++] = cur[i];
    std::swap(cur, alt);
  }
  int64_t out = 0;
  for (int64_t i = 0; i < e; i++) {
    if (dedup && out > 0 && cur[i] == cur[out - 1]) continue;
    cur[out++] = cur[i];
  }
  for (int64_t i = 0; i < out; i++) {
    dst[i] = static_cast<int64_t>(cur[i] >> 32);
    src[i] = static_cast<int64_t>(cur[i] & 0xFFFFFFFFu);
  }
  return out;
}

}  // extern "C"
