"""Graph-workloads CLI — the dataflow-core workloads beyond plain
PageRank (ISSUE 9): batched personalized PageRank, HITS, connected
components.

Usage::

    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.workloads \
        ppr edges.txt --queries 1,2 7 9,12 --iterations 50 --tol 1e-8
    python -m ...cli.workloads hits edges.txt --top-k 10
    python -m ...cli.workloads cc synthetic:10000,40000

(The fourth ISSUE 9 workload, BM25, is the serving layer's second
ranker: ``cli.tfidf --save-index`` bundles it, ``cli.serve --ranker
bm25`` / an ``@bm25`` query prefix selects it per request.)
"""

from __future__ import annotations

import argparse
import json
import sys

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    load_snap,
    synthetic_powerlaw,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    ComponentsConfig,
    HitsConfig,
    PageRankConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer


def _load_graph(spec: str):
    if spec.startswith("synthetic:"):
        parts = spec.split(":", 1)[1].split(",")
        n, e = int(parts[0]), int(parts[1])
        seed = int(parts[2]) if len(parts) > 2 else 0
        return synthetic_powerlaw(n, e, seed=seed)
    return load_snap(spec)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="workloads",
        description="dataflow-core graph workloads: ppr / hits / cc.",
    )
    sub = p.add_subparsers(dest="workload", required=True)

    ppr = sub.add_parser("ppr", help="batched personalized PageRank")
    ppr.add_argument("input", help="SNAP edge list or 'synthetic:N,E[,seed]'")
    ppr.add_argument("--queries", nargs="+", required=True, metavar="IDS",
                     help="one personalization set per query, as "
                          "comma-separated ORIGINAL node ids (e.g. "
                          "'--queries 1,2 7' = two queries)")
    ppr.add_argument("--iterations", type=int, default=50)
    ppr.add_argument("--tol", type=float, default=1e-8)
    ppr.add_argument("--damping", type=float, default=0.85)
    ppr.add_argument("--spmv-impl", default="segment",
                     choices=["segment", "bcoo", "cumsum", "cumsum_mxu",
                              "hybrid", "sort_shuffle", "pallas"])
    ppr.add_argument("--dtype", default="float32")
    ppr.add_argument("--top-k", type=int, default=10)

    hits = sub.add_parser("hits", help="HITS hubs/authorities")
    hits.add_argument("input")
    hits.add_argument("--iterations", type=int, default=100)
    hits.add_argument("--tol", type=float, default=1e-8)
    hits.add_argument("--dtype", default="float32")
    hits.add_argument("--top-k", type=int, default=10)

    cc = sub.add_parser("cc", help="connected components (label propagation)")
    cc.add_argument("input")
    cc.add_argument("--iterations", type=int, default=200)
    cc.add_argument("--output", help="write '<node>\\t<component>' lines here")

    for s in (ppr, hits, cc):
        s.add_argument("--metrics-json")
        s.add_argument("--trace-dir", default=None)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    with obs.run(f"workload_{args.workload}", trace_dir=args.trace_dir):
        return _main(args)


def _main(args) -> int:
    metrics = MetricsRecorder()
    with Timer() as t_load:
        graph = _load_graph(args.input)
    metrics.record(event="load", nodes=graph.n_nodes, edges=graph.n_edges,
                   secs=t_load.elapsed)

    if args.workload == "ppr":
        from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.ppr import (
            run_ppr_batch,
        )

        queries = [[int(x) for x in q.split(",") if x] for q in args.queries]
        cfg = PageRankConfig(
            iterations=args.iterations, tol=args.tol, damping=args.damping,
            dangling="redistribute", init="uniform",
            spmv_impl=args.spmv_impl, dtype=args.dtype,
        )
        res = run_ppr_batch(graph, cfg, queries, metrics=metrics)
        for qi in range(len(queries)):
            order = res.ranks[qi].argsort()[::-1][: args.top_k]
            for i in order:
                print(f"{qi}\t{graph.node_ids[i]}\t{res.ranks[qi][i]:.10g}")
        summary = {"queries": len(queries), "iterations": res.iterations,
                   "l1_delta": res.l1_delta}
    elif args.workload == "hits":
        from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.hits import (
            run_hits,
        )

        res = run_hits(graph, HitsConfig(iterations=args.iterations,
                                         tol=args.tol, dtype=args.dtype),
                       metrics=metrics)
        for name, vec in (("hub", res.hubs), ("auth", res.authorities)):
            order = vec.argsort()[::-1][: args.top_k]
            for i in order:
                print(f"{name}\t{graph.node_ids[i]}\t{vec[i]:.10g}")
        summary = {"iterations": res.iterations, "l1_delta": res.l1_delta}
    else:  # cc
        from page_rank_and_tfidf_using_apache_spark_tpu.dataflow.components import (
            run_components,
        )

        res = run_components(
            graph, ComponentsConfig(iterations=args.iterations),
            metrics=metrics,
        )
        if not res.converged:
            print(f"warning: label propagation hit the {args.iterations}-"
                  "round cap before the fixpoint — the component split is "
                  "an over-segmentation; rerun with more --iterations",
                  file=sys.stderr)
        if args.output:
            with open(args.output, "w") as f:
                for i, lab in enumerate(res.labels):
                    f.write(f"{graph.node_ids[i]}\t{graph.node_ids[lab]}\n")
        summary = {"n_components": res.n_components,
                   "iterations": res.iterations,
                   "converged": res.converged}

    summary.update(nodes=graph.n_nodes, edges=graph.n_edges)
    print(json.dumps(summary), file=sys.stderr)
    if args.metrics_json:
        metrics.dump(args.metrics_json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
