"""Serving CLI — the long-lived query process over a built index
(ISSUE 8).

Usage::

    # build an index once (see also: cli.tfidf --save-index)
    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.tfidf \
        corpus.txt --lines --save-index /data/index

    # serve queries against it (one query per line, space-separated terms)
    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.serve \
        /data/index --queries queries.txt --top-k 10

With ``--queries -`` (the default) queries stream from stdin, so the
process can sit behind a pipe indefinitely — the artifact is mapped once,
the compiled batch runners stay warm, and every request rides the padded
micro-batch path.  Output: one ``<query#>\t<doc>\t<score>`` line per hit;
a summary JSON (stats + latency percentiles) lands on stderr at exit.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
    ServeConfig,
    ServerShutdown,
    TfidfServer,
    load_index,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    load_tuned_profile,
    tuned_config,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve",
        description="Serve top-k TF-IDF queries from a built index artifact.",
    )
    p.add_argument("index", help="index directory (serving.artifact layout)")
    p.add_argument("--version", type=int, default=None,
                   help="serve this index version (default: LATEST)")
    p.add_argument("--queries", default="-",
                   help="file of queries, one per line ('-' = stdin)")
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--max-batch", type=int, default=None,
                   help="micro-batch cap (padded shapes are powers of two; "
                        "default: tuned profile, then TUNABLE_DEFAULTS)")
    p.add_argument("--max-query-terms", type=int, default=16)
    p.add_argument("--cache-size", type=int, default=1024,
                   help="hot-query LRU entries (0 disables)")
    p.add_argument("--ranker", choices=["tfidf", "bm25", "prior"],
                   default="tfidf",
                   help="default scoring weights per request (the index "
                        "must bundle BM25 weights for bm25 — cli.tfidf "
                        "--save-index does by default; 'prior' blends the "
                        "index's PageRank prior per request, needs "
                        "--prior-alpha > 0).  A query line may override "
                        "per request with an '@tfidf '/'@bm25 '/'@prior ' "
                        "prefix — the A/B switch.")
    p.add_argument("--rank-alpha", type=float, default=0.0,
                   help="blend the index's PageRank prior into EVERY "
                        "request (score + alpha * rank; needs an index "
                        "built with ranks)")
    p.add_argument("--prior-alpha", type=float, default=0.0,
                   help="per-REQUEST PageRank-prior scale: enables the "
                        "'prior' ranker (@prior prefix) for exactly the "
                        "queries that opt in")
    p.add_argument("--scoring", choices=["coo", "impacted"], default="coo",
                   help="serving path: 'coo' scores every query batch "
                        "against the full postings; 'impacted' slices only "
                        "the batch's query terms' posting runs from the "
                        "CSC-by-term layout (byte-equal results, work "
                        "proportional to the query, not the corpus)")
    p.add_argument("--impact-bucket-width", type=int, default=None,
                   help="fixed bucket width the impacted planner pads "
                        "posting runs to (default: tuned profile, then "
                        "TUNABLE_DEFAULTS)")
    p.add_argument("--tuned-profile", default=None, metavar="PATH",
                   help="tuned-profile artifact to resolve unset knobs "
                        "from ('off' disables profile loading; default: "
                        "$GRAFT_TUNED_PROFILE, then the committed "
                        "tuned_profile_<backend>.json)")
    p.add_argument("--no-mmap", action="store_true",
                   help="copy the index into RAM instead of mapping it")
    p.add_argument("--trace-dir", default=None,
                   help="obs run-telemetry dir (default: $GRAFT_TRACE_DIR)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    with obs.run("serve", trace_dir=args.trace_dir):
        return _main(args)


def _main(args) -> int:
    from page_rank_and_tfidf_using_apache_spark_tpu.serving import (
        segments as sgm,
    )

    # A segmented index directory (delta commits of the streaming ingest)
    # serves its whole live set — merged on device; a plain artifact
    # directory serves its LATEST version exactly as before.
    if args.version is None and sgm.manifest_version(args.index) is not None:
        index = sgm.load_segment_set(args.index, mmap=not args.no_mmap)
    else:
        index = load_index(args.index, version=args.version,
                           mmap=not args.no_mmap)
    # knob resolution ladder: explicit flag > tuned profile (same-backend
    # only, ProvenanceError otherwise) > TUNABLE_DEFAULTS
    profile = (None if args.tuned_profile == "off"
               else load_tuned_profile(path=args.tuned_profile))
    cfg = tuned_config(
        ServeConfig, profile,
        top_k=args.top_k,
        max_batch=args.max_batch,
        max_query_terms=args.max_query_terms,
        cache_size=args.cache_size,
        rank_alpha=args.rank_alpha,
        prior_alpha=args.prior_alpha,
        scoring=args.scoring,
        impact_bucket_width=args.impact_bucket_width,
    )
    # Live SLO telemetry (ISSUE 11): with GRAFT_METRICS_PORT set, the
    # serve process exposes /snapshot.json + /metrics over the default
    # hub (fed from the bus's serve_request events) — inspect it while it
    # runs with tools/slo_watch.py.
    exporter = obs.export.serve_metrics_from_env()
    source = sys.stdin if args.queries == "-" else open(args.queries)
    lat: list[float] = []
    shutdown = False

    # Graceful SIGTERM (the rolling-restart building block): raising from
    # the handler aborts whatever blocking read/wait the main thread is in
    # (PEP 475 does not retry when the handler raises), we stop accepting,
    # drain every already-accepted request, and the server's stop() fails
    # anything left with the typed ServerShutdown — a supervisor's TERM
    # never hangs a piped client.
    def _on_sigterm(signum, frame):
        raise ServerShutdown("SIGTERM")

    try:
        prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        prev_sigterm = None  # not the main thread (tests drive _main directly)
    try:
        # stdin is request/response: a client writing one query and
        # waiting for output must get its answer before this process
        # reads the next line (the micro-batcher still coalesces queries
        # arriving within one flush window via other submitters).  A
        # query FILE is throughput mode: keep a full batch in flight.
        interactive = source is sys.stdin
        with TfidfServer(index, cfg) as srv:
            pending = []
            try:
                for qid, line in enumerate(source):
                    terms = line.split()
                    if not terms:
                        continue
                    ranker = args.ranker
                    if terms[0] in ("@tfidf", "@bm25", "@prior"):  # per-request A/B
                        ranker = terms[0][1:]
                        terms = terms[1:]
                        if not terms:
                            continue
                    try:
                        pending.append((qid, srv.submit(terms, ranker=ranker)))
                    except ValueError as exc:
                        # one bad line (e.g. '@bm25' against an index without
                        # BM25 weights) must not kill the serve session —
                        # report it and keep draining the stream
                        print(f"query {qid}: {exc}", file=sys.stderr)
                        continue
                    if interactive:
                        while pending:
                            _drain_one(pending, lat)
                    else:
                        # drain in submit order: eagerly when already
                        # resolved, blocking only to bound the window
                        while pending and pending[0][1].done:
                            _drain_one(pending, lat)
                        while len(pending) > cfg.max_batch:
                            _drain_one(pending, lat)
            except ServerShutdown:
                shutdown = True
                obs.emit("serve_sigterm", pending=len(pending))
            # accepted requests drain to completion even on SIGTERM; any
            # future the stopping server failed surfaces typed, not hung
            while pending:
                try:
                    _drain_one(pending, lat)
                except ServerShutdown as exc:
                    print(f"shutdown: request failed: {exc}", file=sys.stderr)
            stats = srv.stats()
    finally:
        if prev_sigterm is not None:
            signal.signal(signal.SIGTERM, prev_sigterm)
        if source is not sys.stdin:
            source.close()
        if exporter is not None:
            exporter.stop()
    stats["shutdown"] = "sigterm" if shutdown else None
    stats["p50_ms"], stats["p99_ms"] = _percentiles_ms(lat)
    print(json.dumps(stats), file=sys.stderr)
    return 0


def _drain_one(pending: list, lat: list[float]) -> None:
    qid, fut = pending.pop(0)
    scores, docs = fut.result()
    lat.append(fut.latency_s or 0.0)
    for s, d in zip(scores, docs):
        if float(s) > 0:
            print(f"{qid}\t{int(d)}\t{float(s):.10g}")
    # stdout is block-buffered behind a pipe; a request/response client
    # must see its answer now, not at process exit
    sys.stdout.flush()


def _percentiles_ms(lat: list[float]) -> tuple[float | None, float | None]:
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        percentile,
    )

    if not lat:
        return None, None
    xs = sorted(lat)
    return (round(percentile(xs, 0.50) * 1e3, 3),
            round(percentile(xs, 0.99) * 1e3, 3))


if __name__ == "__main__":
    sys.exit(main())
