"""TF-IDF CLI — the reference's ``spark-submit tfidf.py <corpus>`` entry
point (SURVEY.md A6, §2.2 R10).

Usage::

    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.tfidf \
        corpus_dir --output weights.tsv --idf-mode classic
    python -m ...cli.tfidf corpus.txt --lines --streaming --chunk-docs 1000
"""

from __future__ import annotations

import argparse
import json
import sys

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
    iter_corpus_chunks,
    iter_corpus_dir,
    iter_corpus_lines,
    load_corpus_dir,
    load_corpus_lines,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
    resume_point,
    run_tfidf,
    run_tfidf_streaming,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    TfidfConfig,
    load_tuned_profile,
    tuned_config,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder
from page_rank_and_tfidf_using_apache_spark_tpu.utils.profiling import trace


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tfidf",
        description="TPU-native TF-IDF over a text corpus (hashed vocabulary).",
    )
    p.add_argument("input", help="corpus directory (one doc per file) or flat file")
    p.add_argument("--lines", action="store_true",
                   help="input is a flat file with one document per line")
    p.add_argument("--output", help="write '<doc>\\t<term_id>\\t<weight>' lines here")
    p.add_argument("--vocab-bits", type=int, default=18)
    p.add_argument("--ngram", type=int, choices=[1, 2], default=1)
    p.add_argument("--tf-mode", choices=["raw", "freq", "lognorm"], default="raw")
    p.add_argument("--idf-mode", choices=["classic", "mllib", "smooth"], default="classic")
    p.add_argument("--l2-normalize", action="store_true")
    p.add_argument("--min-token-len", type=int, default=1)
    p.add_argument("--streaming", action="store_true")
    p.add_argument("--chunk-docs", type=int, default=1024,
                   help="docs per streaming chunk")
    p.add_argument("--chunk-tokens", type=int, default=0,
                   help="fixed token capacity per chunk (0 = auto)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="chunks between checkpoints")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--mesh", type=int, default=0,
                   help="with --streaming: data-parallel ingest over this "
                        "many devices (the BASELINE config-5 'TPU mesh' "
                        "path); 0 = single device")
    p.add_argument("--prefetch", type=int, default=None,
                   help="tokenizer chunks to double-buffer ahead of device "
                        "compute (0 = serial; default: tuned profile, then "
                        "TUNABLE_DEFAULTS)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="H2D-staged chunks the ingest transfer thread may "
                        "hold in device memory — chunk N+1's device_put "
                        "runs under chunk N's compute (0 = stage inline; "
                        "default: tuned profile, then TUNABLE_DEFAULTS)")
    p.add_argument("--pack-target", type=int, default=None, metavar="TOKENS",
                   help="re-pack incoming chunks to ~TOKENS tokens each "
                        "before padding, so half-full chunks stop paying "
                        "full-cap compute (0 = keep the source chunking; "
                        "resume runs must re-use the same value; default: "
                        "tuned profile, then TUNABLE_DEFAULTS)")
    p.add_argument("--tuned-profile", default=None, metavar="PATH",
                   help="tuned-profile artifact to resolve unset knobs "
                        "from ('off' disables profile loading; default: "
                        "$GRAFT_TUNED_PROFILE, then the committed "
                        "tuned_profile_<backend>.json)")
    p.add_argument("--save-index", default=None, metavar="DIR",
                   help="serialize the result as the next servable index "
                        "version under DIR (serving/artifact.py) — the "
                        "input of `cli.serve`")
    p.add_argument("--index-ranks", default=None, metavar="NPY",
                   help="with --save-index: bundle this [n_docs] PageRank "
                        "prior (.npy) into the artifact")
    p.add_argument("--no-index-bm25", action="store_true",
                   help="with --save-index: skip bundling the BM25 "
                        "second-ranker weights (bundled by default — "
                        "same postings, different weighting; enables "
                        "cli.serve --ranker bm25 / per-request A/B)")
    p.add_argument("--bm25-k1", type=float, default=1.5,
                   help="BM25 k1 (term-frequency saturation; default 1.5)")
    p.add_argument("--bm25-b", type=float, default=0.75,
                   help="BM25 b (length normalization; default 0.75)")
    p.add_argument("--query", nargs="+", default=None, metavar="TERM",
                   help="score docs against these terms, print top-k")
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--metrics-json")
    p.add_argument("--profile-dir")
    p.add_argument("--trace-dir", default=None,
                   help="obs run-telemetry dir: write <name>.<pid>.trace.jsonl"
                        " + manifest here (default: $GRAFT_TRACE_DIR)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.mesh and not args.streaming:
        raise SystemExit("--mesh requires --streaming (chunked ingest)")
    # The traced run covers the whole driver: manifest at startup, every
    # span/retry/checkpoint event flushed per-event to the JSONL trace,
    # run-end summary at exit (no-op without --trace-dir/GRAFT_TRACE_DIR).
    with obs.run("tfidf", trace_dir=args.trace_dir):
        return _main(args)


def _main(args) -> int:
    metrics = MetricsRecorder()

    if args.streaming:
        # Lazy iteration: the corpus never fully materializes on host.
        docs = (iter_corpus_lines if args.lines else iter_corpus_dir)(args.input)
        names: list[str] = []
    else:
        docs, names = (load_corpus_lines if args.lines else load_corpus_dir)(args.input)
    # knob resolution ladder: explicit flag > tuned profile (same-backend
    # only, ProvenanceError otherwise) > TUNABLE_DEFAULTS
    profile = (None if args.tuned_profile == "off"
               else load_tuned_profile(path=args.tuned_profile))
    cfg = tuned_config(
        TfidfConfig, profile,
        vocab_bits=args.vocab_bits,
        ngram=args.ngram,
        tf_mode=args.tf_mode,
        idf_mode=args.idf_mode,
        l2_normalize=args.l2_normalize,
        min_token_len=args.min_token_len,
        chunk_tokens=args.chunk_tokens,
        prefetch=args.prefetch,
        pipeline_depth=args.pipeline_depth,
        pack_target_tokens=args.pack_target,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
    )
    # On resume, probe the checkpoint for the restart chunk so the chunker
    # never materializes the already-ingested prefix on host (chunk-level
    # resumable streaming: indices stay stable, documents are not re-read).
    # The checkpoint's ingested doc count rides along so a changed
    # --chunk-docs is rejected instead of silently skipping the wrong docs.
    skip, skip_docs = 0, None
    if args.streaming and args.resume:
        skip = resume_point(cfg)
        if skip:
            from page_rank_and_tfidf_using_apache_spark_tpu.utils import (
                checkpoint as ckpt,
            )

            meta = ckpt.peek_meta(ckpt.latest_checkpoint(cfg.checkpoint_dir))
            skip_docs = int(meta["extra"]["n_docs"])
    with trace(args.profile_dir):
        if args.streaming and args.mesh:
            from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
                run_tfidf_sharded,
            )

            out = run_tfidf_sharded(
                iter_corpus_chunks(docs, args.chunk_docs, skip_chunks=skip,
                                   expect_skipped_docs=skip_docs),
                cfg, n_devices=args.mesh, metrics=metrics, resume=args.resume,
            )
        elif args.streaming:
            out = run_tfidf_streaming(
                iter_corpus_chunks(docs, args.chunk_docs, skip_chunks=skip,
                                   expect_skipped_docs=skip_docs),
                cfg, metrics=metrics, resume=args.resume,
            )
        else:
            out = run_tfidf(docs, cfg, metrics=metrics, doc_names=names)

    if args.save_index:
        import numpy as np

        from page_rank_and_tfidf_using_apache_spark_tpu.serving import save_index
        from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
            Bm25Config,
        )

        ranks = np.load(args.index_ranks) if args.index_ranks else None
        bm25 = (None if args.no_index_bm25 or out.count is None
                else Bm25Config(k1=args.bm25_k1, b=args.bm25_b))
        path = save_index(args.save_index, out, cfg, ranks=ranks, bm25=bm25)
        print(json.dumps({"index": path, "bm25": bm25 is not None}),
              file=sys.stderr)

    if args.output:
        with open(args.output, "w") as f:
            for d, t, w in zip(out.doc, out.term, out.weight):
                f.write(f"{names[d] if d < len(names) else d}\t{t}\t{w:.10g}\n")

    if args.query:
        import jax.numpy as jnp
        import numpy as np

        from page_rank_and_tfidf_using_apache_spark_tpu.io.text import (
            fnv1a_64,
            hash_to_vocab,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.ops.tfidf import TfidfResult, score_query

        q = np.zeros(cfg.vocab_size, np.float32)
        terms = [t.lower() if cfg.lowercase else t for t in args.query]
        q[hash_to_vocab(fnv1a_64(terms), cfg.vocab_bits)] = 1.0
        res = TfidfResult(
            doc=jnp.asarray(out.doc), term=jnp.asarray(out.term),
            weight=jnp.asarray(out.weight),
            n_pairs=jnp.asarray(out.nnz), valid=jnp.ones(out.nnz, jnp.float32),
            idf=jnp.asarray(out.idf), df=jnp.asarray(out.df),
        )
        k = min(args.top_k, max(out.n_docs, 1))
        scores, idx = score_query(res, jnp.asarray(q), n_docs=max(out.n_docs, 1), k=k)
        for s, i in zip(scores, idx):
            if float(s) > 0:
                print(f"{names[int(i)] if int(i) < len(names) else int(i)}\t{float(s):.10g}")

    print(json.dumps({"docs": out.n_docs, "nnz": out.nnz}), file=sys.stderr)
    if args.metrics_json:
        metrics.dump(args.metrics_json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
