"""PageRank CLI — the reference's ``spark-submit pagerank.py <edges>
<iters>`` entry point (SURVEY.md A1/A5, §2.2 R10), positional args first,
every reconstructed-semantics ambiguity an explicit flag.

Usage::

    python -m page_rank_and_tfidf_using_apache_spark_tpu.cli.pagerank \
        edges.txt 20 --output ranks.txt --dangling redistribute
"""

from __future__ import annotations

import argparse
import json
import sys

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
    load_snap,
    save_ranks,
    synthetic_powerlaw,
)
from page_rank_and_tfidf_using_apache_spark_tpu.models.pagerank import run_pagerank
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    load_tuned_profile,
    tuned_config,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder, Timer
from page_rank_and_tfidf_using_apache_spark_tpu.utils.profiling import trace


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pagerank",
        description="TPU-native PageRank over a SNAP-format edge list.",
    )
    p.add_argument("input", help="SNAP edge-list file, or 'synthetic:N,E[,seed]'")
    p.add_argument("iterations", nargs="?", type=int, default=20)
    p.add_argument("--output", help="write '<node>\\t<rank>' lines here")
    p.add_argument("--top-k", type=int, default=None, help="only save the top-k ranks")
    p.add_argument("--damping", type=float, default=0.85)
    p.add_argument("--tol", type=float, default=0.0, help="early-stop L1 tolerance")
    p.add_argument("--dangling", choices=["drop", "redistribute"], default="drop")
    p.add_argument("--init", choices=["one", "uniform"], default="one")
    p.add_argument("--spark-exact", action="store_true",
                   help="bit-exact canonical Spark example semantics")
    p.add_argument("--personalize", type=int, nargs="+", default=None,
                   metavar="NODE",
                   help="personalized PageRank source node(s), as ORIGINAL "
                        "ids from the input file")
    p.add_argument("--spmv-impl",
                   choices=["segment", "bcoo", "cumsum", "cumsum_mxu",
                            "hybrid", "sort_shuffle", "pallas"],
                   default="segment")
    p.add_argument("--head-coverage", type=float, default=None,
                   help="hybrid impl/strategy: edge-coverage threshold of "
                        "the dense high-in-degree head (default: tuned "
                        "profile, then TUNABLE_DEFAULTS)")
    p.add_argument("--head-row-width", type=int, default=None,
                   help="hybrid impl/strategy: dense row width (MXU lane "
                        "count; adapts down on small graphs; default: tuned "
                        "profile, then TUNABLE_DEFAULTS)")
    p.add_argument("--tuned-profile", default=None, metavar="PATH",
                   help="tuned-profile artifact to resolve unset knobs "
                        "from ('off' disables profile loading; default: "
                        "$GRAFT_TUNED_PROFILE, then the committed "
                        "tuned_profile_<backend>.json)")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--metrics-json", help="dump structured metrics JSON here")
    p.add_argument("--profile-dir", help="jax.profiler trace output dir")
    p.add_argument("--trace-dir", default=None,
                   help="obs run-telemetry dir: write <name>.<pid>.trace.jsonl"
                        " + manifest here (default: $GRAFT_TRACE_DIR)")
    p.add_argument("--mesh", type=int, default=0,
                   help="shard over this many devices (0 = single device)")
    p.add_argument("--shard-strategy",
                   choices=["auto", "edges", "nodes", "nodes_balanced",
                            "src", "src_ring", "hybrid", "owned"],
                   default="auto",
                   help="graph partition under --mesh: auto (by memory "
                        "footprint + degree shape) / balanced edge slices / "
                        "node blocks / edge-balanced node blocks (power-law) "
                        "/ source-block push with reduce-scatter (or "
                        "explicit ppermute-ring) exchange / degree-aware "
                        "hybrid (dense MXU head rows + tail edge slices)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # The traced run covers the whole driver: manifest at startup, every
    # span/retry/checkpoint event flushed per-event to the JSONL trace,
    # run-end summary at exit (no-op without --trace-dir/GRAFT_TRACE_DIR).
    with obs.run("pagerank", trace_dir=args.trace_dir):
        return _main(args)


def _main(args) -> int:
    metrics = MetricsRecorder()

    with Timer() as t_load:
        if args.input.startswith("synthetic:"):
            parts = args.input.split(":", 1)[1].split(",")
            n, e = int(parts[0]), int(parts[1])
            seed = int(parts[2]) if len(parts) > 2 else 0
            graph = synthetic_powerlaw(n, e, seed=seed)
        else:
            graph = load_snap(args.input)
    metrics.record(event="load", nodes=graph.n_nodes, edges=graph.n_edges,
                   secs=t_load.elapsed)

    # knob resolution ladder: explicit flag > tuned profile (same-backend
    # only, ProvenanceError otherwise) > TUNABLE_DEFAULTS
    profile = (None if args.tuned_profile == "off"
               else load_tuned_profile(path=args.tuned_profile))
    cfg = tuned_config(
        PageRankConfig, profile,
        iterations=args.iterations,
        damping=args.damping,
        tol=args.tol,
        dangling=args.dangling,
        init=args.init,
        spark_exact=args.spark_exact,
        personalize=tuple(args.personalize) if args.personalize else None,
        spmv_impl=args.spmv_impl,
        head_coverage=args.head_coverage,
        head_row_width=args.head_row_width,
        dtype=args.dtype,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
    )

    with trace(args.profile_dir):
        if args.mesh:
            try:
                from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
                    pagerank_sharded,
                )
            except ImportError:
                print("error: the multi-chip sharded path (parallel/) is not "
                      "present in this build; drop --mesh", file=sys.stderr)
                return 2

            result = pagerank_sharded.run_pagerank_sharded(
                graph, cfg, n_devices=args.mesh, strategy=args.shard_strategy,
                metrics=metrics, resume=args.resume,
            )
        else:
            result = run_pagerank(graph, cfg, metrics=metrics, resume=args.resume)

    if args.output:
        save_ranks(args.output, graph, result.ranks, top_k=args.top_k)
    else:
        order = result.ranks.argsort()[::-1][: args.top_k or 10]
        for i in order:
            print(f"{graph.node_ids[i]}\t{result.ranks[i]:.10g}")

    summary = {
        "nodes": graph.n_nodes, "edges": graph.n_edges,
        "iterations": result.iterations, "l1_delta": result.l1_delta,
    }
    print(json.dumps(summary), file=sys.stderr)
    if args.metrics_json:
        metrics.dump(args.metrics_json)
    return 0


if __name__ == "__main__":
    sys.exit(main())
