"""graftlint tier 3: static cost-model analysis of registered jit entry
points.

Tier 2 (semantic.py) checks what a jaxpr *does* — collectives, callbacks,
dtypes.  This tier checks what it *costs*, still with zero dispatch: every
:class:`~.registry.EntryPoint` is traced on the CPU backend from abstract
``ShapeDtypeStruct`` inputs and three budget surfaces are gated:

- **intensity-floor** — a static FLOP / HBM-byte model over the traced
  equations (per *step*: loop bodies counted once, exactly tier 2's
  convention).  Bytes are the un-fused operand+result traffic of every
  leaf equation, so the modeled intensity is a *lower bound* on what a
  fusing compiler achieves — a conservative, internally consistent ratchet.
  An entry whose worst-variant intensity drops below its declared
  ``intensity_floor`` fails lint... unless the cost baseline artifact
  (``xla_cost_tpu.json``) was measured on a non-TPU backend, in which case
  the finding is **downgraded to advisory**: CPU-measured numbers must
  never gate kernel design (the round-5 tunnel-down failure mode — see
  utils/artifacts.py, which keeps a CPU run from silently overwriting a
  TPU-stamped artifact in the first place).
- **pad-frac-budget** — the static padding-waste analyzer: each entry's
  ``pad_plan`` evaluates its partition/padding strategy *plan* without
  materializing it (``parallel.pagerank_sharded.plan_partition`` for the
  shard strategies, ``models.tfidf.stream_pad_plan`` for the chunk-ingest
  ``grow_chunk_cap`` policy) and the worst plan point must stay under the
  declared ``pad_frac_ceiling``.  ``partition_graph`` materializes exactly
  the plan the linter budgets, and the plan numbers are cross-checked
  against the dryrun-measured ``pad_frac`` in MULTICHIP_r05.json by
  tests/test_cost_lint.py — so a partitioning change that inflates padding
  waste fails lint before any chip sees it.
- **donation-contract** — the buffer-donation verifier: entries declaring
  ``donate`` argnums are *lowered* (still CPU, still no execution) and the
  input/output aliasing recorded in the computation is compared against
  the contract, in both directions: a declared-but-absent donation (the
  un-donated ingest carry this tier's first sweep existed to catch) and an
  undeclared aliased input (a donation the registry does not know about)
  are both findings.

Every check honors the entry's ``suppress`` set, and findings flow through
the same fingerprint/baseline/ratchet machinery as tiers 1 and 2.  A
registry entry that fails to build/trace is a ``cost-entry-broken``
finding (tier 2 reports the same breakage as ``entry-point-broken``; the
distinct rule id keeps the two tiers' ratchet entries independent).
"""

from __future__ import annotations

import dataclasses
import math
from pathlib import Path
from typing import Any, Iterable, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    ENTRY_POINTS,
    EntryPoint,
    Traceable,
    build_traceable,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.semantic import (
    _anchor_location,
    _CALLBACK_PRIMS,
    _COMM_PRIMS,
    _iter_subjaxprs,
    _trace_signature,
    ensure_cpu_tracing_env,
)

COST_RULES: dict[str, str] = {
    "intensity-floor": (
        "static FLOP/byte arithmetic intensity fell below the entry's "
        "declared floor — the program got more memory-bound; advisory "
        "while the cost baseline artifact is not TPU-measured"
    ),
    "pad-frac-budget": (
        "static padding-waste fraction of the entry's partition/padding "
        "plan exceeds its declared ceiling — more dispatched work is "
        "padding than the budget allows"
    ),
    "donation-contract": (
        "declared donate argnums disagree with the lowered computation's "
        "input/output aliasing — a donation that does not happen (or one "
        "the registry does not declare)"
    ),
    "cost-entry-broken": (
        "a registered jit entry point no longer builds, traces or lowers "
        "for the tier-3 cost model — the registry contract is stale"
    ),
}

# Default cost baseline artifact: the XLA op-cost probe output.  Tier 3
# only reads its backend stamp — CPU-measured numbers downgrade the
# intensity ratchet to advisory (they must never gate kernel design).
COST_BASELINE_ARTIFACT = "xla_cost_tpu.json"

# --------------------------------------------------------------------------
# the per-equation FLOP/byte model
# --------------------------------------------------------------------------

# Container primitives: the eqn itself is free; its body is the cost.
_CONTAINERS = frozenset({
    "pjit", "jit", "xla_call", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "remat_call",
    "checkpoint", "scan", "while", "cond", "shard_map", "named_call",
})

# ~10 VPU ops per element: good enough to rank transcendental-heavy code.
_TRANSCENDENTAL = frozenset({
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan", "logistic",
    "erf", "erfc", "pow", "atan2", "cbrt",
})
_SQRTISH = frozenset({"sqrt", "rsqrt"})
_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_window_sum",
    "reduce_window_max",
})
# Prefix scans: modeled at one add per element (XLA's actual lowering is
# O(n log n) HBM passes on TPU — which is exactly why cumsum_blocked and
# the Pallas carry kernel exist; the *model* stays lowering-agnostic).
_SCANS = frozenset({"cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"})
_GATHERISH = frozenset({"gather", "take", "dynamic_slice", "take_along_axis"})
_SCATTERISH = frozenset({
    "scatter", "scatter-add", "scatter_add", "scatter-mul", "scatter_mul",
    "scatter-min", "scatter-max", "dynamic_update_slice", "segment_sum",
})
_MOVES = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "concatenate", "slice",
    "pad", "rev", "squeeze", "expand_dims", "copy", "convert_element_type",
    "bitcast_convert_type", "select_n", "stop_gradient", "device_put",
})
_MATERIALIZE = frozenset({"iota", "broadcast_in_dim"})


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for dim in shape:
        try:
            n *= int(dim)
        except (TypeError, ValueError):  # symbolic dim: count as 1
            pass
    try:
        import numpy as np

        return n * np.dtype(dtype).itemsize
    except TypeError:
        return 0


def _var_elems(v) -> int:
    shape = getattr(getattr(v, "aval", None), "shape", None)
    if shape is None:
        return 0
    n = 1
    for dim in shape:
        try:
            n *= int(dim)
        except (TypeError, ValueError):
            pass
    return n


def _out_elems(eqn) -> int:
    return max(sum(_var_elems(v) for v in eqn.outvars), 1)


def _in_elems(eqn) -> int:
    return max(sum(_var_elems(v) for v in eqn.invars), 1)


def _dot_flops(eqn) -> int:
    """2·batch·M·N·K from dot_general's dimension numbers."""
    try:
        (contract, batch) = eqn.params["dimension_numbers"]
        lhs_c, _ = contract
        lhs = eqn.invars[0].aval.shape
        k = 1
        for dim in lhs_c:
            k *= int(lhs[dim])
        out = 1
        for dim in eqn.outvars[0].aval.shape:
            out *= int(dim)
        return 2 * out * max(k, 1)
    except Exception:
        return 2 * _out_elems(eqn)


def classify_eqn(eqn) -> tuple[str, int]:
    """(cost class, flops) for one leaf equation."""
    name = eqn.primitive.name
    if name == "dot_general":
        return "matmul", _dot_flops(eqn)
    if name in _CALLBACK_PRIMS:
        return "callback", 0
    if name in _COMM_PRIMS:
        return "comm", 0
    if name == "pallas_call":
        # Opaque on purpose: the kernel body runs in VMEM; its HBM cost is
        # the operands/results this eqn reads and writes.
        return "pallas", _out_elems(eqn)
    if name == "sort":
        n = _in_elems(eqn)
        return "sort", n * max(int(math.log2(max(n, 2))), 1)
    if name == "top_k":
        return "sort", _in_elems(eqn)
    if name in _SCANS:
        return "scan-prefix", _in_elems(eqn)
    if name in _REDUCE:
        return "reduce", _in_elems(eqn)
    if name in _GATHERISH:
        return "gather", 0
    if name in _SCATTERISH:
        # the combine runs once per UPDATE element (E for a segment_sum
        # into N bins), not per output element — take the largest operand
        largest = max(
            (_var_elems(v) for v in eqn.invars), default=_out_elems(eqn)
        )
        return "scatter", largest
    if name == "iota":
        return "materialize", 0
    if name in _MOVES:
        return "move", 0
    if name in _TRANSCENDENTAL:
        return "elementwise", 10 * _out_elems(eqn)
    if name in _SQRTISH:
        return "elementwise", 4 * _out_elems(eqn)
    # default: one VPU op per output element (add/mul/compare/...)
    return "elementwise", _out_elems(eqn)


def _leaf_eqns(jaxpr) -> Iterable[Any]:
    """Leaf (cost-bearing) equations of ``jaxpr``: container eqns (pjit,
    scan/while/cond bodies, shard_map...) are recursed into, not counted —
    their operands are exactly their body's operands, and counting both
    would double every byte.  Loop bodies are therefore counted ONCE: the
    model is per *step*, matching tier 2's census convention.

    Containment is decided by the ``_CONTAINERS`` allowlist, NOT by
    "carries a jaxpr param": primitives like ``scatter-add`` embed a tiny
    update jaxpr (one scalar add) while their real cost is the E-sized
    operand traffic of the eqn itself — recursing into those would erase
    exactly the segment_sum/scatter class this model exists to weigh.
    pallas_call is likewise a leaf (its body lives in VMEM, not HBM)."""
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            subs: list = []
            if eqn.primitive.name in _CONTAINERS:
                for v in eqn.params.values():
                    subs.extend(_iter_subjaxprs(v))
            if subs:
                stack.extend(subs)
            else:
                yield eqn


@dataclasses.dataclass
class CostSummary:
    """Static per-step cost model of one traced variant."""

    flops: int = 0
    bytes_read: int = 0
    bytes_written: int = 0
    comm_bytes: int = 0  # collective operand bytes (ICI, not HBM)
    materialized_bytes: int = 0  # iota/broadcast expansion + closed consts
    callback_eqns: int = 0
    eqns: int = 0
    classes: dict = dataclasses.field(default_factory=dict)

    @property
    def hbm_bytes(self) -> int:
        return self.bytes_read + self.bytes_written

    @property
    def intensity(self) -> float:
        return self.flops / max(self.hbm_bytes, 1)

    def to_dict(self) -> dict:
        top = sorted(
            self.classes.items(),
            key=lambda kv: kv[1]["bytes"],
            reverse=True,
        )
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "intensity": round(self.intensity, 6),
            "comm_bytes": self.comm_bytes,
            "materialized_bytes": self.materialized_bytes,
            "callback_eqns": self.callback_eqns,
            "eqns": self.eqns,
            "classes": {k: v for k, v in top},
        }


def summarize_jaxpr(closed) -> CostSummary:
    """Walk a ClosedJaxpr and accumulate the static cost model."""
    import numpy as np

    s = CostSummary()
    for const in closed.consts:
        dtype = getattr(const, "dtype", None)
        shape = getattr(const, "shape", None)
        if dtype is None or shape is None:
            continue
        n = 1
        for dim in shape:
            n *= int(dim)
        s.materialized_bytes += n * np.dtype(dtype).itemsize
    for eqn in _leaf_eqns(closed.jaxpr):
        cls, flops = classify_eqn(eqn)
        read = sum(_aval_bytes(v) for v in eqn.invars)
        written = sum(_aval_bytes(v) for v in eqn.outvars)
        s.eqns += 1
        s.flops += flops
        s.bytes_read += read
        s.bytes_written += written
        if cls == "comm":
            s.comm_bytes += read
        if cls == "callback":
            s.callback_eqns += 1
        if cls == "materialize" or (
            eqn.primitive.name in _MATERIALIZE and written > read
        ):
            s.materialized_bytes += written
        c = s.classes.setdefault(cls, {"eqns": 0, "flops": 0, "bytes": 0})
        c["eqns"] += 1
        c["flops"] += flops
        c["bytes"] += read + written
    return s


# --------------------------------------------------------------------------
# baseline provenance
# --------------------------------------------------------------------------


def baseline_backend(path: Path) -> str | None:
    """Backend stamp of the cost baseline artifact (``"tpu"``, ``"cpu"``,
    or None when the artifact is missing/unreadable/unstamped) — the same
    reader the write-time provenance guard uses, so the two can never
    disagree about a stamp."""
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.artifacts import (
        read_backend,
    )

    return read_backend(path)


# --------------------------------------------------------------------------
# the tier-3 analyzer
# --------------------------------------------------------------------------


@dataclasses.dataclass
class CostResult:
    """Tier-3 output: gating findings, non-gating advisories (intensity
    regressions while the cost baseline is not TPU-measured), and the full
    per-entry cost report for ``--cost-report``."""

    findings: list[Finding]
    advisories: list[Finding]
    report: dict

    @property
    def ok(self) -> bool:
        return not self.findings


def _analyze_entry_cost(
    ep: EntryPoint, root: Path, enforce_intensity: bool
) -> tuple[list[Finding], list[Finding], dict]:
    import jax

    findings: list[Finding] = []
    advisories: list[Finding] = []
    report: dict = {"entry": ep.name, "variants": {}}

    def add(rule: str, message: str, t: Traceable | None,
            advisory: bool = False) -> None:
        if rule in ep.suppress:
            return
        path, line, snippet = _anchor_location(ep, t, root)
        f = Finding(rule=rule, path=path, line=line, col=0,
                    message=f"[{ep.name}] {message}", snippet=snippet)
        (advisories if advisory else findings).append(f)

    try:
        t = build_traceable(ep)
    except Exception as exc:
        add("cost-entry-broken",
            f"entry point failed to build: {type(exc).__name__}: {exc}", None)
        return findings, advisories, report

    # ---- trace once per distinct signature; model each
    sigs: dict[tuple, tuple[str, tuple]] = {}
    for label, args in t.variants:
        sigs.setdefault(_trace_signature(jax, args), (label, args))
    worst: tuple[float, str] | None = None  # (intensity, label)
    for label, args in sigs.values():
        try:
            closed = jax.make_jaxpr(t.fn)(*args)
        except Exception as exc:
            add("cost-entry-broken",
                f"tracing variant {label!r} failed: "
                f"{type(exc).__name__}: {exc}", t)
            return findings, advisories, report
        summary = summarize_jaxpr(closed)
        report["variants"][label] = summary.to_dict()
        if worst is None or summary.intensity < worst[0]:
            worst = (summary.intensity, label)

    # ---- intensity-floor (ratchet; advisory without a TPU baseline)
    if ep.intensity_floor is not None and worst is not None:
        report["intensity_floor"] = ep.intensity_floor
        if worst[0] < ep.intensity_floor:
            add(
                "intensity-floor",
                f"static arithmetic intensity {worst[0]:.4f} flop/byte in "
                f"variant {worst[1]!r} fell below the declared floor "
                f"{ep.intensity_floor} — the step got more memory-bound"
                + ("" if enforce_intensity else
                   f" [ADVISORY: {COST_BASELINE_ARTIFACT} is not "
                   "TPU-measured; re-run the cost tools on a real TPU to "
                   "arm this gate]"),
                t,
                advisory=not enforce_intensity,
            )

    # ---- pad-frac-budget (static plan analyzer; backend-independent)
    if ep.pad_plan is not None:
        try:
            plan_points = list(ep.pad_plan())
        except Exception as exc:
            add("cost-entry-broken",
                f"pad plan failed: {type(exc).__name__}: {exc}", t)
            plan_points = []
        report["pad_plan"] = {lbl: round(frac, 4) for lbl, frac in plan_points}
        if ep.pad_frac_ceiling is not None and plan_points:
            report["pad_frac_ceiling"] = ep.pad_frac_ceiling
            worst_pad = max(plan_points, key=lambda p: p[1])
            if worst_pad[1] > ep.pad_frac_ceiling:
                add(
                    "pad-frac-budget",
                    f"static pad_frac {worst_pad[1]:.4f} at plan point "
                    f"{worst_pad[0]!r} exceeds the declared ceiling "
                    f"{ep.pad_frac_ceiling} — more than the budgeted "
                    "fraction of dispatched work is padding",
                    t,
                )

    # ---- donation-contract (lowered input/output aliasing verifier)
    if ep.donate is not None:
        label, args = t.variants[0]
        fn = t.donate_fn if t.donate_fn is not None else t.fn
        kwargs = dict(t.donate_kwargs or {})
        # jax drops donation from the lowering while debug_nans/debug_infs
        # are on (the NaN re-run needs the inputs alive).  Production never
        # runs with them; the test env does — lower with both off so the
        # verifier sees the aliasing production gets.
        dbg = [("jax_debug_nans", jax.config.jax_debug_nans),
               ("jax_debug_infs", jax.config.jax_debug_infs)]
        for knob, _ in dbg:
            jax.config.update(knob, False)
        try:
            if not hasattr(fn, "lower"):
                fn = jax.jit(fn)
            lowered = fn.lower(*args, **kwargs)
            text = lowered.as_text()
        except Exception as exc:
            add("cost-entry-broken",
                f"lowering variant {label!r} for the donation check "
                f"failed: {type(exc).__name__}: {exc}", t)
        else:
            expected = sum(
                len(jax.tree_util.tree_leaves(args[i])) for i in ep.donate
            )
            actual = text.count("tf.aliasing_output")
            report["donation"] = {"declared_buffers": expected,
                                  "aliased_buffers": actual}
            if actual < expected:
                add(
                    "donation-contract",
                    f"declares donate argnums {list(ep.donate)} "
                    f"({expected} buffer(s)) but the lowered computation "
                    f"aliases only {actual} input buffer(s) — the donation "
                    "does not happen (missing donate_argnums, or a "
                    "shape/dtype mismatch makes the donated buffer "
                    "unusable)",
                    t,
                )
            elif actual > expected:
                add(
                    "donation-contract",
                    f"lowered computation aliases {actual} input buffer(s) "
                    f"but the registry declares {expected} — an undeclared "
                    "donation; callers re-invoking with a consumed buffer "
                    "will fail on backends with real donation",
                    t,
                )
        finally:
            for knob, value in dbg:
                jax.config.update(knob, value)
    return findings, advisories, report


def run_cost(
    root: Path | None = None,
    entries: Sequence[EntryPoint] | None = None,
    only_modules: set[str] | None = None,
    baseline_path: Path | None = None,
) -> CostResult:
    """Run the tier-3 static cost analysis.

    Same restriction contract as :func:`semantic.run_semantic`:
    ``only_modules`` limits the run to entries whose module/watch set
    intersects it.  ``baseline_path`` overrides the cost baseline artifact
    whose backend stamp decides whether the intensity ratchet gates
    (TPU-measured) or advises (anything else).
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import repo_root

    root = root or repo_root()
    ensure_cpu_tracing_env()
    bl_path = baseline_path or (root / COST_BASELINE_ARTIFACT)
    backend = baseline_backend(bl_path)
    enforce_intensity = backend == "tpu"
    findings: list[Finding] = []
    advisories: list[Finding] = []
    report: dict = {
        "baseline_artifact": str(bl_path),
        "baseline_backend": backend,
        "intensity_gate": "enforcing" if enforce_intensity else "advisory",
        "entries": [],
    }
    for ep in entries if entries is not None else ENTRY_POINTS:
        if only_modules is not None and not (
            {ep.module, *ep.watch} & only_modules
        ):
            continue
        f, a, rep = _analyze_entry_cost(ep, root, enforce_intensity)
        findings.extend(f)
        advisories.extend(a)
        report["entries"].append(rep)
    return CostResult(
        findings=assign_fingerprints(findings),
        advisories=assign_fingerprints(advisories),
        report=report,
    )
