"""graftlint tier 4: interprocedural concurrency & buffer-lifetime
analysis of the threaded runtime (ISSUE 12).

Spark gets its concurrency safety from process isolation — executors, the
driver and the block manager are separate JVMs, so a hung or racy
component cannot corrupt its peers.  This one-process rebuild packs the
same roles into threads (ingest tokenize/H2D stages, the server drain
thread, the soak supervisor + closed-loop clients + prior-refresh thread,
the metrics hub, the HTTP exporter) plus donated device buffers whose
misuse is silent corruption, not a crash.  Tier 4 is the static gate for
exactly that defect class.  Like tier 1 it is stdlib-only — pure AST over
the scan surface, no jax import, a whole-repo run in well under the
declared ``GRAFT_CONC_BUDGET_S`` budget — but unlike tier 1 it builds ONE
repo-wide model (locks, threads, guarded sites, donation contracts) and
checks cross-cutting invariants over it:

- **lock-order-cycle** — the lock-acquisition graph across every
  ``threading.Lock``/``RLock`` site (module- and instance-scoped), with
  same-file call propagation (a function called while a lock is held
  contributes its own acquisitions as edges), must be cycle-free.  A
  cycle is a potential deadlock the GIL will not save you from.  The
  graph itself is exported as DOT/JSON via ``--lock-graph``.
- **blocking-under-lock** — a blocking call (``queue.get/put`` on a
  bounded queue, ``Future.result``, thread ``join``, ``Event.wait``,
  ``time.sleep``, HTTP I/O, subprocess, or any guarded device sync)
  reachable while a lock is held serializes every other thread that
  needs the lock behind an unbounded wait.
- **use-after-donate** — operands passed at a donated position of a
  declared donating callee (``analysis/registry.py DONATED_CALLEES``,
  validated both directions against the ``EntryPoint.donate``
  declarations) are *consumed*: any later host-side read of that binding,
  any re-dispatch of it, and any donating call inside a retry closure
  (``run_guarded``/``retry_transient`` re-invoke their fn — the exact
  hazard models/pagerank.py dodges by hand at ``pagerank_delta_sync``)
  is flagged.  The safe idiom — ``counts, carry = kernel(..., carry)``
  rebinding in the consuming statement — stays quiet.
- **chaos-coverage-drift** — every guarded site name in models//parallel/
  /dataflow//serving/ (``run_guarded`` / ``retry_transient`` /
  ``attempt_once`` / guarded ``device_get`` / ``block_until_ready``) is
  cross-referenced against the chaos plans tests and ``tools/chaos.sh``
  actually inject (named sites only — a ``*`` wildcard proves nothing
  about a specific site's recovery path), so a new guarded site cannot
  land without a fault-injection test.  F-string sites resolve to their
  literal suffix (``f"{prefix}_step"`` is covered once any named chaos
  site ends in ``_step``).
- **thread-lock-drift** — every declared thread's target (plus same-file
  callees) may acquire only the locks its ``utils/config.py
  THREAD_REGISTRY`` row declares; the name-side validation lives in tier
  1 (``thread-registry-drift``).

Findings flow through the same suppression (``# graftlint:
disable=<rule>``) and fingerprint/baseline/ratchet machinery as every
other tier.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Any, Iterator

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import (
    FileContext,
    FuncNode,
    call_name,
    dotted_name,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    default_targets,
    iter_python_files,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import (
    _names_match,
    resolve_thread_name,
    thread_registry_rows,
)

CONC_RULES: dict[str, str] = {
    "lock-order-cycle": (
        "cycle in the repo-wide lock-acquisition graph (nested with-blocks "
        "plus same-file call propagation) — two threads taking the locks "
        "in opposite orders deadlock"
    ),
    "blocking-under-lock": (
        "blocking call (queue get/put, Future.result, join, Event.wait, "
        "sleep, HTTP/subprocess, guarded device sync) reachable while a "
        "lock is held — every peer needing the lock stalls behind it"
    ),
    "use-after-donate": (
        "a binding passed at a donated position of a declared donating "
        "callee is read host-side or re-dispatched after the consuming "
        "call (or dispatched from inside a retry closure) — donated "
        "buffers are dead after dispatch; also contract drift between "
        "DONATED_CALLEES and the registry donate declarations"
    ),
    "chaos-coverage-drift": (
        "a guarded site in models//parallel//dataflow//serving/ is named "
        "by no chaos-injection test or tools/chaos.sh scenario — its "
        "retry/recovery path ships unexercised"
    ),
    "thread-lock-drift": (
        "a registered thread's target acquires a lock outside its "
        "THREAD_REGISTRY declaration — the declared thread/lock inventory "
        "and the code must not drift"
    ),
}

_GUARDED_TREE_DIRS = frozenset({"models", "parallel", "dataflow", "serving"})

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "Lock": "Lock",
    "RLock": "RLock",
}
_QUEUE_CTOR_LEAVES = frozenset(
    {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
)
_EVENT_CTOR_LEAVES = frozenset({"Event", "Condition", "Semaphore", "Barrier"})
_THREAD_CTOR_LEAVES = frozenset({"Thread"})

_RETRY_FAMILY = frozenset({"run_guarded", "retry_transient", "attempt_once"})
_GUARDED_WRAPPER_LEAVES = frozenset({"device_get", "block_until_ready"})
_GUARDED_WRAPPER_ROOTS = frozenset({"", "rx", "executor", "resilience.executor"})

# host-side reads that touch a (possibly consumed) device buffer
_HOST_READ_CALLS = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "rx.device_get", "executor.device_get", "device_get",
    "float", "int",
})
_HOST_READ_METHODS = frozenset({"block_until_ready", "item", "tolist"})

_CHAOS_TOKEN_RE = re.compile(
    r"([A-Za-z_][A-Za-z0-9_]*)"
    r":(?:fail|lost|hang|device_lost|proc_kill|net_partition|net_hang)@"
)


# --------------------------------------------------------------------------
# per-file model
# --------------------------------------------------------------------------


def _walk_own(node: ast.AST, *, include_self: bool = True) -> Iterator[ast.AST]:
    """Walk without descending into nested function definitions."""
    if include_self:
        yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield from _walk_own(child)


class _FileModel:
    """Per-file facts the repo-wide checks consume."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.defs_by_name: dict[str, list[FuncNode]] = {}
        self.all_funcs: list[FuncNode] = []
        self.enclosing_class_cache: dict[ast.AST, str | None] = {}
        self.module_str_consts: dict[str, str] = {}
        self.lock_decls: dict[str, str] = {}  # lock id -> "Lock" | "RLock"
        self.queue_names: set[str] = set()
        self.event_names: set[str] = set()
        self.thread_names: set[str] = set()

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
                self.all_funcs.append(node)
            elif isinstance(node, ast.Lambda):
                self.all_funcs.append(node)

        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    isinstance(stmt.value, ast.Constant) and \
                    isinstance(stmt.value.value, str):
                self.module_str_consts[stmt.targets[0].id] = stmt.value.value

        self._collect_decls_and_taints()

    # -------------------------------------------------------------- helpers

    def enclosing_class(self, node: ast.AST) -> str | None:
        if node in self.enclosing_class_cache:
            return self.enclosing_class_cache[node]
        cur = self.ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                self.enclosing_class_cache[node] = cur.name
                return cur.name
            cur = self.ctx.parents.get(cur)
        self.enclosing_class_cache[node] = None
        return None

    def _collect_decls_and_taints(self) -> None:
        for node in ast.walk(self.ctx.tree):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], node.value
            if value is None:
                continue
            ctor = self._ctor_kind(value)
            if ctor is None:
                continue
            kind, leaf = ctor
            for t in targets:
                spelled: str | None = None
                is_self_attr = False
                if isinstance(t, ast.Name):
                    spelled = t.id
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    spelled = t.attr
                    is_self_attr = True
                if spelled is None:
                    continue
                if kind == "lock":
                    # the id mirrors the acquisition spelling: self attrs
                    # (and class-body field declarations, the dataclass
                    # idiom) scope to their class, bare names to the module
                    class_body_decl = (
                        not is_self_attr
                        and self.ctx.enclosing_function(node) is None
                        and self.enclosing_class(node) is not None
                    )
                    cls = (self.enclosing_class(node)
                           if (is_self_attr or class_body_decl) else None)
                    lid = (f"{self.relpath}::{cls}.{spelled}" if cls
                           else f"{self.relpath}::{spelled}")
                    self.lock_decls[lid] = leaf
                elif kind == "queue":
                    self.queue_names.add(spelled)
                elif kind == "event":
                    self.event_names.add(spelled)
                elif kind == "thread":
                    self.thread_names.add(spelled)

    def _ctor_kind(self, value: ast.expr) -> tuple[str, str] | None:
        """Classify an assignment RHS as a lock/queue/event/thread ctor.
        Also sees through ``dataclasses.field(default_factory=threading.
        Lock)`` (the MetricsRecorder idiom)."""
        if not isinstance(value, ast.Call):
            return None
        cname = call_name(value)
        if cname in _LOCK_CTORS:
            return ("lock", _LOCK_CTORS[cname])
        if cname is not None:
            leaf = cname.rsplit(".", 1)[-1]
            if leaf in _QUEUE_CTOR_LEAVES and (
                cname == leaf or cname.startswith("queue.")
            ):
                return ("queue", leaf)
            if leaf in _EVENT_CTOR_LEAVES and (
                cname == leaf or cname.startswith("threading.")
            ):
                return ("event", leaf)
            if leaf in _THREAD_CTOR_LEAVES and (
                cname == leaf or cname.startswith("threading.")
            ):
                return ("thread", leaf)
            if leaf == "field":
                for kw in value.keywords:
                    if kw.arg == "default_factory":
                        inner = dotted_name(kw.value)
                        if inner in _LOCK_CTORS:
                            return ("lock", _LOCK_CTORS[inner])
        return None

    def lock_id_for(self, expr: ast.AST, node: ast.AST) -> str | None:
        """Lock identity of a ``with <expr>:`` context expression, or None
        when the expression is not lock-flavored (same lexical heuristic
        as tier 1's ``_is_lockish``: the dotted spelling mentions "lock")."""
        name = dotted_name(expr)
        if name is None or "lock" not in name.lower():
            return None
        if name.startswith("self."):
            cls = self.enclosing_class(node)
            rest = name[5:]
            return (f"{self.relpath}::{cls}.{rest}" if cls
                    else f"{self.relpath}::{rest}")
        return f"{self.relpath}::{name}"

    def same_file_callees(self, call: ast.Call) -> list[FuncNode]:
        f = call.func
        if isinstance(f, ast.Name):
            return self.defs_by_name.get(f.id, [])
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return self.defs_by_name.get(f.attr, [])
        return []


# --------------------------------------------------------------------------
# the lock graph
# --------------------------------------------------------------------------


@dataclasses.dataclass
class LockGraph:
    """Repo-wide lock-acquisition graph: nodes are lock identities
    (``<module>::<scope>.<attr>``), an edge A -> B means code acquires B
    while holding A (directly nested or through a same-file call chain)."""

    nodes: dict[str, dict] = dataclasses.field(default_factory=dict)
    edges: dict[tuple[str, str], dict] = dataclasses.field(default_factory=dict)
    threads: list[dict] = dataclasses.field(default_factory=list)

    def add_node(self, lid: str, kind: str | None, path: str, line: int) -> None:
        self.nodes.setdefault(
            lid, {"kind": kind or "unknown", "path": path, "line": line}
        )

    def add_edge(self, src: str, dst: str, path: str, line: int,
                 via: str) -> None:
        self.edges.setdefault(
            (src, dst), {"path": path, "line": line, "via": via}
        )

    def to_json(self) -> dict:
        return {
            "nodes": {
                lid: dict(meta) for lid, meta in sorted(self.nodes.items())
            },
            "edges": [
                {"src": a, "dst": b, **meta}
                for (a, b), meta in sorted(self.edges.items())
            ],
            "threads": list(self.threads),
        }

    def to_dot(self) -> str:
        def q(s: str) -> str:
            return '"' + s.replace('"', '\\"') + '"'

        lines = ["digraph lock_graph {", "  rankdir=LR;",
                 "  node [shape=box, fontsize=10];"]
        for lid, meta in sorted(self.nodes.items()):
            label = f"{lid}\\n({meta['kind']})"
            lines.append(f"  {q(lid)} [label={q(label)}];")
        for (a, b), meta in sorted(self.edges.items()):
            lines.append(
                f"  {q(a)} -> {q(b)} "
                f"[label={q(meta['path'] + ':' + str(meta['line']))}];"
            )
        for t in self.threads:
            tid = f"thread:{t['name']}"
            lines.append(f"  {q(tid)} [shape=ellipse, label={q(tid)}];")
            for lid in t.get("locks", []):
                lines.append(f"  {q(tid)} -> {q(lid)} [style=dashed];")
        lines.append("}")
        return "\n".join(lines)


# --------------------------------------------------------------------------
# blocking-call classification
# --------------------------------------------------------------------------


def _receiver_spelling(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


def _receiver_attr(call: ast.Call) -> str | None:
    """Last attribute/name component of the receiver (``self._q`` -> _q)."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = call.func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr
    if isinstance(recv, ast.Name):
        return recv.id
    return None


def _blocking_kind(model: _FileModel, node: ast.Call) -> str | None:
    cname = call_name(node)
    leaf = None
    if cname is not None:
        leaf = cname.rsplit(".", 1)[-1]
    elif isinstance(node.func, ast.Attribute):
        leaf = node.func.attr
    if leaf is None:
        return None
    root = ""
    if cname is not None and "." in cname:
        root = cname[: -len(leaf) - 1]

    if cname == "time.sleep":
        return "time.sleep"
    if leaf == "urlopen" and root in ("urllib.request", "request", ""):
        return "HTTP I/O (urlopen)"
    if root == "subprocess" and leaf in ("run", "call", "check_call",
                                         "check_output"):
        return f"subprocess.{leaf}"
    if leaf in _RETRY_FAMILY:
        return f"guarded call ({leaf})"
    if leaf in _GUARDED_WRAPPER_LEAVES and (
        root in _GUARDED_WRAPPER_ROOTS or root == "jax"
    ):
        return f"device sync ({leaf})"
    if leaf == "block_until_ready" and isinstance(node.func, ast.Attribute) \
            and not node.args:
        return "device sync (.block_until_ready())"
    if leaf == "result" and len(node.args) <= 1 and not node.keywords \
            and isinstance(node.func, ast.Attribute) \
            and not isinstance(node.func.value, ast.Constant):
        return "Future.result"
    attr = _receiver_attr(node)
    if leaf in ("get", "put") and attr is not None and (
        attr in model.queue_names or "queue" in attr.lower()
    ):
        return f"queue.{leaf}"
    if leaf == "join":
        spelled = (_receiver_spelling(node) or "").lower()
        if (attr is not None and attr in model.thread_names) \
                or "thread" in spelled:
            return "thread join"
    if leaf == "wait" and attr is not None and attr in model.event_names:
        return "Event.wait"
    return None


# --------------------------------------------------------------------------
# the under-lock walker (blocking-under-lock + lock-graph edges)
# --------------------------------------------------------------------------


class _WalkState:
    def __init__(self, graph: LockGraph, findings: "_Sink"):
        self.graph = graph
        self.findings = findings
        self.visited: set[tuple[int, frozenset]] = set()
        self.blocked_seen: set[tuple[str, int, str]] = set()


def _scan_under_locks(
    model: _FileModel,
    fn: FuncNode,
    node: ast.AST,
    held: tuple[str, ...],
    state: _WalkState,
    chain: tuple[str, ...],
) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            and node is not fn:
        return  # nested definitions execute later, not under this lock

    if isinstance(node, (ast.With, ast.AsyncWith)):
        acquired: list[str] = []
        for item in node.items:
            lid = model.lock_id_for(item.context_expr, node)
            if lid is not None:
                kind = model.lock_decls.get(lid)
                state.graph.add_node(lid, kind, model.relpath, node.lineno)
                for h in held:
                    state.graph.add_edge(
                        h, lid, model.relpath, node.lineno,
                        via=" -> ".join(chain) if chain else "direct",
                    )
                acquired.append(lid)
            else:
                _scan_under_locks(model, fn, item.context_expr, held, state,
                                  chain)
        new_held = held + tuple(acquired)
        for stmt in node.body:
            _scan_under_locks(model, fn, stmt, new_held, state, chain)
        return

    if isinstance(node, ast.Call):
        if held:
            kind = _blocking_kind(model, node)
            if kind is not None:
                key = (model.relpath, node.lineno, kind)
                if key not in state.blocked_seen:
                    state.blocked_seen.add(key)
                    via = (f" (reached via {' -> '.join(chain)})"
                           if chain else "")
                    state.findings.add(
                        model.ctx, "blocking-under-lock", node,
                        f"blocking call {kind} while holding "
                        f"{', '.join(held)}{via} — every thread needing the "
                        "lock stalls behind this wait; move the blocking "
                        "call outside the critical section or bound it",
                    )
            for callee in model.same_file_callees(node):
                vkey = (id(callee), frozenset(held))
                if vkey not in state.visited:
                    state.visited.add(vkey)
                    fname = getattr(callee, "name", "<lambda>")
                    body = callee.body if isinstance(callee.body, list) \
                        else [callee.body]
                    for stmt in body:
                        _scan_under_locks(
                            model, callee, stmt, held, state,
                            chain + (f"{fname}()",),
                        )

    for child in ast.iter_child_nodes(node):
        _scan_under_locks(model, fn, child, held, state, chain)


def _reachable_acquisitions(model: _FileModel,
                            roots: list[FuncNode]) -> set[str]:
    """Every lock id acquired by ``roots`` or their same-file callees
    (thread-target reachability — like tier 1's ``_thread_targets`` but
    also resolving ``self.method()`` calls)."""
    acquired: set[str] = set()
    seen: set[int] = set()
    stack = list(roots)
    while stack:
        fn = stack.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in _walk_own(stmt):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lid = model.lock_id_for(item.context_expr, node)
                        if lid is not None:
                            acquired.add(lid)
                elif isinstance(node, ast.Call):
                    stack.extend(model.same_file_callees(node))
    return acquired


# --------------------------------------------------------------------------
# finding sink (suppression-aware)
# --------------------------------------------------------------------------


class _Sink:
    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()

    def add(self, ctx: FileContext, rule: str, node: ast.AST | None,
            message: str, *, path: str | None = None,
            line: int | None = None) -> None:
        path = path or ctx.relpath
        line = line if line is not None else getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) if node is not None else 0
        if ctx.is_suppressed(rule, line):
            return
        key = (rule, path, line, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(
            Finding(rule=rule, path=path, line=line, col=col,
                    message=message, snippet=ctx.snippet(line))
        )


# --------------------------------------------------------------------------
# lock-order cycles
# --------------------------------------------------------------------------


def _find_cycles(graph: LockGraph) -> list[list[str]]:
    """Strongly connected components of size > 1, plus self-loops on
    non-reentrant locks."""
    adj: dict[str, set[str]] = {}
    for (a, b) in graph.edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                out.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for (a, b) in graph.edges:
        if a == b and graph.nodes.get(a, {}).get("kind") == "Lock":
            out.append([a])
    return out


def _check_lock_cycles(graph: LockGraph, models: dict[str, _FileModel],
                       sink: _Sink) -> None:
    for comp in _find_cycles(graph):
        comp_set = set(comp)
        cyc_edges = [
            ((a, b), meta) for (a, b), meta in graph.edges.items()
            if a in comp_set and b in comp_set
        ]
        if not cyc_edges:
            continue
        (a, b), meta = min(
            cyc_edges, key=lambda e: (e[1]["path"], e[1]["line"])
        )
        model = models.get(meta["path"])
        if model is None:
            continue
        if len(comp) == 1:
            msg = (
                f"non-reentrant lock {comp[0]} is re-acquired while already "
                "held — self-deadlock; use an RLock or restructure the "
                "critical section"
            )
        else:
            msg = (
                "lock-order cycle: " + " -> ".join(comp + [comp[0]]) +
                " — threads taking these locks in different orders can "
                "deadlock; impose one global acquisition order"
            )
        sink.add(model.ctx, "lock-order-cycle", None, msg,
                 path=meta["path"], line=meta["line"])


# --------------------------------------------------------------------------
# use-after-donate
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _DonationContract:
    rows: tuple  # (leaf, argnums, entries)
    entries: dict  # entry name -> donate argnums (non-empty only)
    path: Path | None  # the registry file resolved
    relpath: str | None  # repo-relative, when under the scanned root
    row_line: int  # lineno of the DONATED_CALLEES assignment
    entry_lines: dict  # entry name -> lineno of its EntryPoint(...) call


_contract_cache: dict[str, _DonationContract | None] = {}


def _parse_contract(path: Path) -> tuple | None:
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    rows: tuple = ()
    row_line = 1
    entries: dict = {}
    entry_lines: dict = {}
    for node in ast.walk(tree):
        dc_value: ast.expr | None = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "DONATED_CALLEES"
            for t in node.targets
        ):
            dc_value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "DONATED_CALLEES":
            dc_value = node.value
        if dc_value is not None:
            row_line = node.lineno
            parsed = []
            if isinstance(dc_value, (ast.Tuple, ast.List)):
                for row in dc_value.elts:
                    if not isinstance(row, (ast.Tuple, ast.List)) \
                            or len(row.elts) != 3:
                        continue
                    leaf_n, argn_n, ents_n = row.elts
                    if not (isinstance(leaf_n, ast.Constant)
                            and isinstance(leaf_n.value, str)):
                        continue
                    argnums = tuple(
                        e.value for e in getattr(argn_n, "elts", [])
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    )
                    ents = tuple(
                        e.value for e in getattr(ents_n, "elts", [])
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    )
                    parsed.append((leaf_n.value, argnums, ents))
            rows = tuple(parsed)
        elif isinstance(node, ast.Call):
            cname = call_name(node) or ""
            if cname.rsplit(".", 1)[-1] != "EntryPoint":
                continue
            name = None
            donate: tuple | None = None
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
                elif kw.arg == "donate" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    donate = tuple(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    )
            if name and donate:
                entries[name] = donate
                entry_lines[name] = node.lineno
    return rows, entries, row_line, entry_lines


def _donation_contract(root: Path) -> _DonationContract | None:
    key = str(root)
    if key in _contract_cache:
        return _contract_cache[key]
    candidates = [
        (root / "page_rank_and_tfidf_using_apache_spark_tpu/analysis/registry.py",
         True),
        (root / "analysis/registry.py", True),
        (Path(__file__).resolve().parent / "registry.py", False),
    ]
    contract = None
    for path, in_root in candidates:
        if path.exists():
            parsed = _parse_contract(path)
            if parsed is None:
                continue
            rows, entries, row_line, entry_lines = parsed
            relpath = None
            if in_root:
                try:
                    relpath = path.resolve().relative_to(
                        root.resolve()
                    ).as_posix()
                except ValueError:
                    relpath = path.as_posix()
            contract = _DonationContract(
                rows=rows, entries=entries, path=path, relpath=relpath,
                row_line=row_line, entry_lines=entry_lines,
            )
            break
    _contract_cache[key] = contract
    return contract


def _validate_contract(contract: _DonationContract,
                       models: dict[str, _FileModel], sink: _Sink) -> None:
    """Both directions: every donating entry served by a row; every row
    entry real and argnum-consistent.  Anchored at the registry file —
    only when it lives under the scanned root."""
    if contract.relpath is None:
        return
    model = models.get(contract.relpath)
    if model is None:
        return
    served: dict[str, tuple] = {}
    for leaf, argnums, ents in contract.rows:
        for e in ents:
            served[e] = argnums
    for name, donate in sorted(contract.entries.items()):
        if name not in served:
            sink.add(
                model.ctx, "use-after-donate", None,
                f"registry entry {name!r} declares donate={list(donate)} "
                "but no DONATED_CALLEES row serves it — the tier-4 "
                "liveness analyzer cannot see its call sites; add the "
                "callee-leaf convention to the contract",
                line=contract.entry_lines.get(name, contract.row_line),
            )
        elif served[name] != donate:
            sink.add(
                model.ctx, "use-after-donate", None,
                f"DONATED_CALLEES serves entry {name!r} with argnums "
                f"{list(served[name])} but the registry declares "
                f"donate={list(donate)} — the lexical contract drifted",
                line=contract.row_line,
            )
    for leaf, argnums, ents in contract.rows:
        for e in ents:
            if e not in contract.entries:
                sink.add(
                    model.ctx, "use-after-donate", None,
                    f"DONATED_CALLEES row {leaf!r} names entry {e!r} which "
                    "no EntryPoint declares with a non-empty donate — "
                    "stale contract row; fix or drop it",
                    line=contract.row_line,
                )


def _stmt_binds(node: ast.AST) -> set[str]:
    names: set[str] = set()

    def targets_of(t: ast.expr) -> Iterator[str]:
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from targets_of(e)
        elif isinstance(t, ast.Starred):
            yield from targets_of(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            names.update(targets_of(t))
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        names.update(targets_of(node.target))
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        names.update(targets_of(node.target))
    return names


def _enclosing_stmt(ctx: FileContext, node: ast.AST) -> ast.AST:
    cur: ast.AST = node
    while True:
        parent = ctx.parents.get(cur)
        if parent is None or isinstance(parent, (
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module,
        )):
            return cur
        if isinstance(cur, ast.stmt):
            return cur
        cur = parent


def _check_use_after_donate_fn(model: _FileModel, fn: FuncNode,
                               leaf_map: dict, sink: _Sink) -> None:
    ctx = model.ctx
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    nodes = []
    for stmt in body:
        nodes.extend(_walk_own(stmt))
    nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                              getattr(n, "col_offset", 0)))

    consumed: dict[str, tuple[int, str]] = {}  # name -> (line, callee leaf)
    # A rebind kills the taint only for STRICTLY LATER lines: the
    # rebinding statement's own RHS still reads the old (dead) binding —
    # ``carry = np.asarray(carry)`` after a consume must flag — so the
    # kill is deferred past the binding line instead of applied in place.
    kill_line: dict[str, int] = {}

    for node in nodes:
        line = getattr(node, "lineno", 0)

        for name, kl in list(kill_line.items()):
            if line > kl:
                consumed.pop(name, None)
                del kill_line[name]

        binds = _stmt_binds(node)
        for name in list(consumed):
            if name in binds and line > consumed[name][0]:
                kill_line[name] = min(kill_line.get(name, line), line)

        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        leaf = cname.rsplit(".", 1)[-1] if cname else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None
        )

        # host-side read of a consumed binding
        read_names: set[str] = set()
        if cname in _HOST_READ_CALLS:
            for a in node.args:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Name):
                        read_names.add(sub.id)
        elif leaf in _HOST_READ_METHODS and not node.args and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name):
            read_names.add(node.func.value.id)
        for name in read_names:
            if name in consumed and line > consumed[name][0]:
                cline, cleaf = consumed[name]
                sink.add(
                    ctx, "use-after-donate", node,
                    f"host-side read of {name!r} after {cleaf}() consumed "
                    f"it at line {cline} (donated operand) — the buffer is "
                    "dead after dispatch; read the kernel's OUTPUT binding "
                    "or pull before donating",
                )

        if leaf not in leaf_map:
            continue
        argnums = leaf_map[leaf]
        stmt = _enclosing_stmt(ctx, node)
        stmt_rebinds = _stmt_binds(stmt)
        for i in argnums:
            if i >= len(node.args):
                continue
            arg = node.args[i]
            if not isinstance(arg, ast.Name):
                continue
            name = arg.id
            if name in consumed and line > consumed[name][0]:
                cline, cleaf = consumed[name]
                sink.add(
                    ctx, "use-after-donate", node,
                    f"re-dispatch of {name!r} into {leaf}() after "
                    f"{cleaf}() already consumed it at line {cline} — the "
                    "donated buffer is dead; thread the kernel's returned "
                    "carry instead",
                )
            elif name not in stmt_rebinds:
                # consumed and NOT rebound by this statement: track it
                consumed[name] = (line, leaf)


def _check_retry_closures(model: _FileModel, leaf_map: dict,
                          sink: _Sink) -> None:
    """The PR-6 ``pagerank_delta_sync`` hazard shape: a donating call
    inside a closure handed to the retry machinery — every retry
    re-dispatches into the buffer the first attempt already consumed."""
    ctx = model.ctx
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname is None or cname.rsplit(".", 1)[-1] not in _RETRY_FAMILY:
            continue
        if not node.args:
            continue
        closure = node.args[0]
        bodies: list[FuncNode] = []
        if isinstance(closure, ast.Lambda):
            bodies = [closure]
        elif isinstance(closure, ast.Name):
            bodies = model.defs_by_name.get(closure.id, [])
        for fn in bodies:
            fn_body = fn.body if isinstance(fn.body, list) else [fn.body]
            assigned_before: set[str] = set()
            flat: list[ast.AST] = []
            for stmt in fn_body:
                flat.extend(_walk_own(stmt))
            flat.sort(key=lambda n: (getattr(n, "lineno", 0),
                                     getattr(n, "col_offset", 0)))
            for sub in flat:
                if isinstance(sub, ast.Call):
                    scname = call_name(sub)
                    sleaf = scname.rsplit(".", 1)[-1] if scname else None
                    if sleaf in leaf_map:
                        for i in leaf_map[sleaf]:
                            if i >= len(sub.args):
                                continue
                            arg = sub.args[i]
                            if isinstance(arg, ast.Name) and \
                                    arg.id not in assigned_before:
                                sink.add(
                                    ctx, "use-after-donate", sub,
                                    f"donating call {sleaf}() inside a "
                                    f"closure passed to {cname} consumes "
                                    f"captured binding {arg.id!r} — a "
                                    "retry re-dispatches into the buffer "
                                    "the first attempt donated (the "
                                    "pagerank_delta_sync hazard); fetch "
                                    "results via their own guarded site "
                                    "and rebuild the carry per attempt",
                                )
                assigned_before |= _stmt_binds(sub)


# --------------------------------------------------------------------------
# chaos-coverage-drift
# --------------------------------------------------------------------------


def _chaos_coverage_tokens(root: Path) -> set[str]:
    names: set[str] = set()
    tests_dir = root / "tests"
    if tests_dir.is_dir():
        for p in sorted(tests_dir.rglob("*.py")):
            try:
                names.update(_CHAOS_TOKEN_RE.findall(
                    p.read_text(encoding="utf-8")))
            except OSError:
                continue
    chaos_sh = root / "tools" / "chaos.sh"
    if chaos_sh.exists():
        try:
            names.update(_CHAOS_TOKEN_RE.findall(
                chaos_sh.read_text(encoding="utf-8")))
        except OSError:
            pass
    return names


def _resolve_site(model: _FileModel, expr: ast.AST,
                  node: ast.AST) -> tuple[str, str] | None:
    """("exact", name) / ("suffix", tail) / None (unresolvable)."""
    ctx = model.ctx
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return ("exact", expr.value)
    if isinstance(expr, ast.JoinedStr):
        last = expr.values[-1] if expr.values else None
        if isinstance(last, ast.Constant) and isinstance(last.value, str) \
                and last.value:
            return ("suffix", last.value)
        return None
    if isinstance(expr, ast.Name):
        if expr.id in model.module_str_consts:
            return ("exact", model.module_str_consts[expr.id])
        fn = ctx.enclosing_function(node)
        if fn is not None and not isinstance(fn, ast.Lambda):
            a = fn.args
            params = a.posonlyargs + a.args
            for p, d in zip(params[len(params) - len(a.defaults):],
                            a.defaults):
                if p.arg == expr.id and isinstance(d, ast.Constant) \
                        and isinstance(d.value, str):
                    return ("exact", d.value)
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if p.arg == expr.id and isinstance(d, ast.Constant) \
                        and isinstance(d.value, str):
                    return ("exact", d.value)
            for sub in _walk_own(fn, include_self=False):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and sub.targets[0].id == expr.id:
                    return _resolve_site(model, sub.value, sub)
    return None


def _check_chaos_coverage(models: dict[str, _FileModel], root: Path,
                          sink: _Sink) -> None:
    tokens = _chaos_coverage_tokens(root)
    for relpath, model in sorted(models.items()):
        parts = relpath.split("/")
        if not (set(parts[:-1]) & _GUARDED_TREE_DIRS):
            continue
        for node in ast.walk(model.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname is None:
                continue
            leaf = cname.rsplit(".", 1)[-1]
            root_part = cname[: -len(leaf) - 1] if "." in cname else ""
            guarded = leaf in _RETRY_FAMILY or (
                leaf in _GUARDED_WRAPPER_LEAVES
                and root_part in _GUARDED_WRAPPER_ROOTS
            )
            if not guarded:
                continue
            site_expr = next(
                (kw.value for kw in node.keywords if kw.arg == "site"), None
            )
            if site_expr is None:
                if leaf in _GUARDED_WRAPPER_LEAVES:
                    resolved: tuple[str, str] | None = ("exact", leaf)
                else:
                    resolved = None
            else:
                resolved = _resolve_site(model, site_expr, node)
            if resolved is None:
                sink.add(
                    model.ctx, "chaos-coverage-drift", node,
                    f"guarded call {cname} has no statically-resolvable "
                    "site name — spell the site as a literal (or an "
                    "f-string with a literal suffix) so chaos coverage "
                    "can be cross-referenced",
                )
                continue
            mode, value = resolved
            if mode == "exact":
                covered = value in tokens
                want = value
            else:
                covered = any(t.endswith(value) for t in tokens)
                want = f"*{value}"
            if not covered:
                sink.add(
                    model.ctx, "chaos-coverage-drift", node,
                    f"guarded site {want!r} is exercised by no chaos-"
                    "injection test or tools/chaos.sh scenario — add a "
                    f"fault-injection test (chaos.inject(\"{want}:fail"
                    "@1\")-style) proving its retry/recovery path, or "
                    "suppress with a justification",
                )


# --------------------------------------------------------------------------
# thread-lock-drift
# --------------------------------------------------------------------------


def _canon_lock(declared: str, module: str) -> str:
    return declared if "::" in declared else f"{module}::{declared}"


def _check_thread_locks(models: dict[str, _FileModel], root: Path,
                        graph: LockGraph, sink: _Sink) -> None:
    rows = thread_registry_rows(root)
    for relpath, model in sorted(models.items()):
        ctx = model.ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in ("threading.Thread", "Thread"):
                continue
            name_expr = next(
                (kw.value for kw in node.keywords if kw.arg == "name"), None
            )
            resolved = resolve_thread_name(ctx, name_expr, node)
            target_expr = next(
                (kw.value for kw in node.keywords if kw.arg == "target"), None
            )
            targets: list[FuncNode] = []
            if isinstance(target_expr, ast.Lambda):
                targets = [target_expr]
            elif isinstance(target_expr, ast.Name):
                targets = model.defs_by_name.get(target_expr.id, [])
            elif isinstance(target_expr, ast.Attribute) and \
                    isinstance(target_expr.value, ast.Name) and \
                    target_expr.value.id == "self":
                targets = model.defs_by_name.get(target_expr.attr, [])
            if not targets:
                continue
            acquired = _reachable_acquisitions(model, targets)
            if resolved is not None:
                graph.threads.append({
                    "name": resolved, "module": relpath,
                    "line": node.lineno, "locks": sorted(acquired),
                })
            if resolved is None or rows is None:
                continue  # tier 1's thread-registry-drift owns naming
            matched = [
                r for r in rows
                if len(r) >= 2 and _names_match(resolved, r[0])
                and r[1] == relpath
            ]
            if not matched:
                continue
            declared: set[str] = set()
            for r in matched:
                locks = r[2] if len(r) >= 3 else ()
                declared |= {_canon_lock(l, relpath) for l in locks}
            for lid in sorted(acquired - declared):
                sink.add(
                    ctx, "thread-lock-drift", node,
                    f"thread {resolved!r} acquires lock {lid} which its "
                    "THREAD_REGISTRY row does not declare — add the lock "
                    "to the declaration (and review the ordering) or "
                    "confine it away from this thread",
                )


# --------------------------------------------------------------------------
# the tier-4 runner
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ConcResult:
    findings: list[Finding]
    graph: LockGraph

    @property
    def ok(self) -> bool:
        return not self.findings


def run_concurrency(
    root: Path | None = None,
    paths: "list[Path] | None" = None,
    only_modules: "set[str] | None" = None,
) -> ConcResult:
    """Run the tier-4 concurrency analysis.

    The repo-wide model is always built over the full ``paths`` surface
    (defaults to the tier-1 surface) — interprocedural facts do not
    restrict — but with ``only_modules`` given, findings are filtered to
    those repo-relative paths (the ``--changed-only`` fast path; the
    model build is pure AST and costs well under a second).
    """
    root = root or repo_root()
    targets = paths if paths is not None else default_targets(root)

    models: dict[str, _FileModel] = {}
    for f in iter_python_files(targets):
        try:
            source = f.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(f))
        except (OSError, SyntaxError):
            continue  # tier 1 reports parse errors
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        ctx = FileContext(rel, source, tree, root=root)
        models[rel] = _FileModel(ctx)

    sink = _Sink()
    graph = LockGraph()

    # declared locks are graph nodes even when never acquired under another
    for model in models.values():
        for lid, kind in model.lock_decls.items():
            graph.add_node(lid, kind, model.relpath, 1)

    # blocking-under-lock + edge collection
    for model in models.values():
        state = _WalkState(graph, sink)
        for fn in model.all_funcs:
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                _scan_under_locks(model, fn, stmt, (), state, ())
        # module-level with-blocks
        for stmt in model.ctx.tree.body:
            _scan_under_locks(model, model.ctx.tree, stmt, (),  # type: ignore[arg-type]
                              state, ())

    _check_lock_cycles(graph, models, sink)

    # use-after-donate
    contract = _donation_contract(root)
    if contract is not None:
        _validate_contract(contract, models, sink)
        leaf_map = {leaf: argnums for leaf, argnums, _ in contract.rows}
        if leaf_map:
            for model in models.values():
                for fn in model.all_funcs:
                    _check_use_after_donate_fn(model, fn, leaf_map, sink)
                _check_retry_closures(model, leaf_map, sink)

    _check_chaos_coverage(models, root, sink)
    _check_thread_locks(models, root, graph, sink)

    findings = sink.findings
    if only_modules is not None:
        findings = [f for f in findings if f.path in only_modules]
    return ConcResult(findings=assign_fingerprints(findings), graph=graph)
