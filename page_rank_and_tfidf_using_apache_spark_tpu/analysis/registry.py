"""Declarative registry of the package's jit entry points for tier-2
(semantic) analysis.

Each :class:`EntryPoint` names one jit-compiled program that production
code dispatches — the PageRank iteration loops (single-chip and sharded),
the TF-IDF batch pipeline, the streaming/sharded chunk-ingest kernels, the
finalize pass and query scoring — together with how to *trace* it on the
CPU backend from abstract ``ShapeDtypeStruct`` inputs: no FLOPs run, only
trace-time Python.  The semantic analyzer (``analysis/semantic.py``)
traces every registered entry under its declared shape matrix and checks
the invariants no lexical rule can see: compile count across the matrix,
64-bit dtype leaks under x64, host callbacks per traced step, and
collective axis names / communication volume against the declared mesh
contract.

Declaring a new jit entry point (see README "Static analysis"):

1. write a ``_build_<name>()`` returning a :class:`Traceable` — the
   function to trace, one ``(label, args)`` variant per point of the shape
   matrix production feeds it (apply the caller's real padding/bucketing
   policy when building the matrix, e.g. ``grow_chunk_cap``), and an
   ``anchor`` (the public function findings should point at);
2. append an :class:`EntryPoint` to ``ENTRY_POINTS`` with the budgets the
   program is designed to meet — ``max_compiles`` (distinct trace
   signatures the matrix may produce), ``transfer_budget`` (host-callback
   eqns per step, almost always 0), and for shard_map'd programs the
   declared ``axes`` plus a ``collective_budget``;
3. ``python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis
   --tier 2`` must stay clean.

jax and the package modules are imported lazily inside the builders so
tier-1 linting never pays (or depends on) a jax import.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

# Shape-matrix sizes for the streaming ingest entries: raw per-chunk token
# counts as production sees them (mixed Wikipedia-scale chunks plus one
# exactly-at-capacity chunk).  The registry feeds them through the REAL
# caller-side padding policy (models.tfidf.grow_chunk_cap); if that policy
# ever stops bucketing, the distinct-signature count jumps past
# ``max_compiles`` and the recompile-per-shape gate fires.
CHUNK_TOKEN_MATRIX = (9_000, 120_000, 97_531, 131_072)


@dataclasses.dataclass(frozen=True)
class Traceable:
    """What the analyzer actually traces for one entry point."""

    fn: Callable  # callable accepting one variant's args
    variants: Sequence[tuple[str, tuple]]  # (label, args) per matrix point
    anchor: Callable | None = None  # public fn findings point at (else fn)
    # Tier-3 donation verifier surface: the *raw jitted* callable to
    # ``.lower()`` (``fn`` may be a partial/dispatch wrapper that hides the
    # jit boundary and its donate_argnums) plus its static kwargs.  None =
    # lower ``fn`` itself.
    donate_fn: Callable | None = None
    donate_kwargs: dict | None = None


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One registered jit entry point plus the budgets it must meet."""

    name: str
    module: str  # repo-relative path of the module under contract
    build: Callable[[], Traceable]
    # Other repo-relative modules the contract depends on (the shape policy
    # a shape matrix runs through, the mesh axis constants...): a
    # --changed-only run re-traces this entry when any of them changed,
    # not just ``module``.
    watch: tuple[str, ...] = ()
    max_compiles: int = 1  # distinct trace signatures the matrix may yield
    transfer_budget: int = 0  # host-callback eqns allowed per traced step
    axes: tuple[str, ...] = ()  # declared mesh axes (shard_map entries)
    collective_budget: int | None = None  # comm eqns per step (None = ungated)
    allow_64bit: bool = False  # opt out of the implicit-promotion gate
    suppress: frozenset = frozenset()  # semantic + cost rule ids to skip
    # ---- tier-3 (analysis/cost.py) budgets ----
    # Minimum static FLOP/HBM-byte arithmetic intensity per step (worst
    # variant).  Gating only while xla_cost_tpu.json carries a TPU backend
    # stamp; advisory otherwise.  None = ungated.
    intensity_floor: float | None = None
    # Static padding-waste budget: ``pad_plan()`` returns (label, pad_frac)
    # plan points evaluated WITHOUT dispatching (plan_partition /
    # stream_pad_plan); the worst point must stay <= pad_frac_ceiling.
    pad_plan: Callable[[], Sequence[tuple[str, float]]] | None = None
    pad_frac_ceiling: float | None = None
    # Buffer-donation contract: positional argnums of the traceable's
    # donate_fn whose buffers the lowered computation must alias to an
    # output.  None = unchecked; () = must alias nothing.
    donate: tuple[int, ...] | None = None


_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"

# ---------------------------------------------------------------------------
# Donation-liveness contract (tier 4, ISSUE 12).
#
# ``EntryPoint.donate`` tells the tier-3 verifier which buffers the LOWERED
# computation must alias; this literal tells the tier-4 *lexical* analyzer
# which call-site spellings consume a donated buffer, so `use-after-donate`
# can dataflow-track the operand a caller passes at a donated position and
# flag any later host-side read (or re-dispatch) of that binding — the
# hazard models/pagerank.py dodges by hand at ``pagerank_delta_sync``.
#
# Each row is ``(callee leaf name as it appears at call sites, donated
# positional argnums, the registry entry names the convention serves)``:
# ``chunk_counts_carry`` is the streaming DF carry kernel called by name;
# ``runner`` is the conventional binding every fixpoint driver gives the
# compiled ``make_*_runner`` product (models/pagerank.py, dataflow/
# fixpoint.py's ``call`` closures), whose carry rides at argnum 1.
#
# The tier-4 analyzer validates this contract against ENTRY_POINTS in both
# directions (every donating entry must be served by a row; every row must
# name real donating entries with matching argnums), so the lexical surface
# and the lowered-aliasing surface cannot drift apart.  Parsed lexically —
# keep it a literal.
DONATED_CALLEES: tuple = (
    ("chunk_counts_carry", (3,), ("tfidf_chunk_ingest_carry",)),
    # the owned sharded runner donates its 4-leaf carry TUPLE at argnum 0
    # (tail slice, replicated head, lagged-delta slots) — _ShardedExec's
    # owned invoke binds the compiled product to this name so the
    # use-after-donate dataflow can see the consumption
    ("owned_runner", (0,), ("pagerank_sharded_owned",)),
    ("runner", (1,), (
        "pagerank_step",
        "pagerank_step_tol_cumsum",
        "pagerank_step_pallas",
        "pagerank_step_hybrid",
        "pagerank_step_sort_shuffle",
        "dataflow_ppr_batch",
        "dataflow_hits",
        "dataflow_components",
    )),
)

# ---------------------------------------------------------------------------
# Persistence contracts (tier 5, ISSUE 14).
#
# ``ARTIFACT_SCHEMAS`` declares every on-disk artifact family the runtime
# commits and reloads — the serving index array-dir, the segment manifest,
# checkpoint metadata, the run manifest, the measured cost artifacts — in
# the same two-way contract style as ``DONATED_CALLEES``: the lexical
# surface (which keys writers store, which keys readers load) and the
# declaration may not drift apart in either direction.
#
# Each row is ``(family, writers, readers, keys, aux_keys)``:
#
# - ``writers`` / ``readers`` are ``"<repo-relative path>::<function>"``
#   specs (``Class.method`` allowed for the function part; readers may
#   append ``::<receiver>`` to scope collection to one dict variable —
#   needed for reader modules like tools/trace_report.py that handle many
#   document shapes in one function);
# - ``keys`` is the family's full declared key space: array members plus
#   META/JSON document keys;
# - ``aux_keys`` (a subset of ``keys``) marks deliberately write-only
#   forensic keys — evidence for humans/ops tooling that no code path
#   loads back (the run manifest's argv/knob snapshot, the index META's
#   corpus stats).
#
# The tier-5 ``schema-pair-drift`` check (analysis/persistence.py)
# validates both directions: every declared key must be written by a
# writer; every non-aux key must be read by a reader (a member saved but
# never loaded — or loaded but never saved — is a finding); every lexical
# write/read of an undeclared key is drift.  Parsed lexically — keep it a
# literal.
ARTIFACT_SCHEMAS: tuple = (
    ("index",
     (f"{_PKG}/serving/artifact.py::save_index",
      f"{_PKG}/serving/segments.py::seal_segment",
      f"{_PKG}/serving/segments.py::merge_segments"),
     (f"{_PKG}/serving/artifact.py::load_index",
      f"{_PKG}/serving/segments.py::load_segment_set",
      f"{_PKG}/serving/segments.py::merge_segments"),
     ("doc", "term", "weight", "idf", "df", "term_offsets", "count",
      "doc_lengths", "ranks", "bm25_weight",
      "format", "n_docs", "vocab_bits", "nnz", "has_ranks", "has_bm25",
      "bm25_config", "tfidf_config", "doc_base", "merged_from"),
     # corpus stats + provenance: ops-facing META evidence; the reader
     # side reconstructs them from SegmentRef/arrays instead
     ("nnz", "has_ranks", "has_bm25", "doc_base", "merged_from")),
    ("segment_manifest",
     (f"{_PKG}/serving/segments.py::_write_manifest",
      f"{_PKG}/serving/segments.py::SegmentRef.to_json"),
     (f"{_PKG}/serving/segments.py::latest_manifest",
      f"{_PKG}/serving/segments.py::_replaced_by",
      f"{_PKG}/serving/segments.py::SegmentRef.from_json"),
     ("version", "config_hash", "n_docs", "nnz", "replaced", "segments",
      "name", "doc_base"),
     ()),
    ("checkpoint_meta",
     (f"{_PKG}/utils/checkpoint.py::save_checkpoint",
      f"{_PKG}/utils/checkpoint.py::save_array_dir"),
     (f"{_PKG}/utils/checkpoint.py::load_checkpoint",
      f"{_PKG}/utils/checkpoint.py::load_array_dir"),
     ("step", "config_hash", "extra"),
     ()),
    ("run_manifest",
     (f"{_PKG}/obs/manifest.py::write_manifest",
      f"{_PKG}/obs/manifest.py::finalize_manifest",
      f"{_PKG}/obs/manifest.py::_device_snapshot"),
     ("tools/trace_report.py::stitch::man",
      "tools/trace_report.py::render_human::man"),
     ("name", "status", "pid", "argv", "python", "started_wall",
      "trace_path", "git_sha", "lint_clean", "knobs", "tuned_profile",
      "backend", "devices",
      "device_count", "finished_wall", "wall_secs", "events", "summary"),
     # the SIGKILL-forensics payload: written for humans reading the file,
     # not reloaded by any code path
     ("argv", "python", "started_wall", "trace_path", "lint_clean",
      "knobs", "tuned_profile", "devices", "device_count", "finished_wall",
      "wall_secs", "events", "summary")),
    ("cost_artifact",
     (f"{_PKG}/utils/artifacts.py::write_artifact",),
     (f"{_PKG}/utils/artifacts.py::read_backend",),
     ("backend",),
     ()),
    # the autotuner's committed per-backend knob optimum (ISSUE 16):
    # written durably by the config layer (stage + durable_replace, same
    # provenance guard as the cost artifacts), loaded back through the one
    # knob-resolution ladder every runner uses.  git_sha/created_wall/
    # measured are sweep forensics — the loader carries them for manifests
    # but no code path branches on them.
    ("tuned_profile",
     (f"{_PKG}/utils/config.py::write_tuned_profile",),
     (f"{_PKG}/utils/config.py::load_tuned_profile::record",),
     ("backend", "knobs", "git_sha", "created_wall", "measured"),
     ()),
    # the serving fleet's committed generation floor (ISSUE 17): one JSON
    # doc next to the segment manifest, staged + durably replaced like
    # every other commit; committed_wall is rollout forensics only
    ("fabric_floor",
     (f"{_PKG}/serving/fabric.py::commit_floor",),
     (f"{_PKG}/serving/fabric.py::read_floor",),
     ("floor", "committed_wall"),
     ("committed_wall",)),
)

# ``COMMIT_LOCKS`` declares which lock serializes each on-disk protocol's
# read-modify-write commit step: ``(module, lock spelled as acquired,
# protected callee leaves)``.  The tier-5 ``commit-lock-drift`` check
# requires every lexical call to a protected callee in that module to sit
# under ``with <lock>`` (reusing tier 4's lock model), and validates the
# declaration itself — the lock and the callees must exist.  Parsed
# lexically — keep it a literal.
COMMIT_LOCKS: tuple = (
    # manifest generations are read-modify-write: an ingest append and a
    # background merge racing unserialized can resurrect replaced segments
    (f"{_PKG}/serving/segments.py", "_COMMIT_LOCK", ("_write_manifest",)),
)

# ---------------------------------------------------------------------------
# Wire-protocol contract (tier 6, ISSUE 18).
#
# ``WIRE_SCHEMAS`` declares the router↔replica HTTP protocol the serving
# fabric rides — every endpoint the fleet serves, in the same two-way
# contract style as ``DONATED_CALLEES``/``ARTIFACT_SCHEMAS``: the lexical
# surface (codes a handler returns, keys it writes, keys the router reads)
# and this declaration may not drift apart in either direction.  A drifted
# status code is a dropped-request class: the router's retry loop can only
# classify what the contract names.
#
# Each row is ``(endpoint, method, path, handler, readers, request_keys,
# response_keys, aux_response_keys, status_classes)``:
#
# - ``handler`` is a ``"<repo-relative path>::<function>[::<receiver>]"``
#   spec; the optional receiver scopes request-key *reads* to the parsed
#   request dict (``handle_query``'s ``req``) so a handler's other dict
#   lookups don't pollute the request surface;
# - ``readers`` are client-side specs (router/health-loop functions), each
#   optionally receiver-scoped the same way for response-key reads;
# - ``request_keys`` / ``response_keys`` are the full declared key spaces;
# - ``aux_response_keys`` (subset of ``response_keys``) marks evidence
#   keys written for harnesses/operators that no in-repo reader loads
#   (the echoed ``rid`` the conformance harness byte-compares, the 503
#   body's ``floor`` diagnostics);
# - ``status_classes`` pairs every status code the endpoint may emit with
#   the router-side class that handles it: ``success`` (consume),
#   ``terminal`` (raise to the caller — never retried), ``retryable``
#   (sibling retry under the SAME rid; 503-below-floor MUST be here), or
#   ``suspect`` (mark the replica and reroute).
#
# The tier-6 checks (analysis/protocol.py) validate both directions, and
# ``tools/protocol_harness.py`` replays the enumerated message space at a
# live replica asserting every observed code is declared.  Parsed
# lexically — keep it a literal.
WIRE_SCHEMAS: tuple = (
    ("query", "POST", "/query",
     f"{_PKG}/serving/fabric.py::_Replica.handle_query::req",
     (f"{_PKG}/serving/fabric.py::ServingFabric.query",),
     ("rid", "terms", "ranker"),
     ("rid", "replica", "generation", "scores", "docs", "error", "floor"),
     # rid/replica/generation: harness- and operator-facing echo; floor:
     # the 503 body's catch-up diagnostic — the router acts on the CODE
     ("rid", "replica", "generation", "floor"),
     ((200, "success"), (400, "terminal"), (503, "retryable"))),
    ("status", "GET", "/status",
     f"{_PKG}/serving/fabric.py::_Replica.handle_status",
     (f"{_PKG}/serving/fabric.py::ServingFabric._health_loop::status",
      f"{_PKG}/serving/fabric.py::ServingFabric.fleet_generation::s",
      f"{_PKG}/serving/fabric.py::ServingFabric.await_fleet_generation::s",
      f"{_PKG}/serving/fabric.py::ServingFabric.rolling_restart::s"),
     (),
     ("replica", "pid", "ready", "generation", "floor", "executions",
      "replays", "p50_ms", "p99_ms", "requests", "cache_hits",
      "refreshes", "peer_hits", "peer_misses", "peek_timeouts", "fills",
      "breaker_open", "peer_stores"),
     # identity + cache forensics: ops-facing, no router branch reads them
     ("replica", "pid", "cache_hits", "refreshes"),
     ((200, "success"),)),
    # sharded-cache peer endpoints (ISSUE 20).  /cache/peek is a pure
    # read (a miss is a SUCCESS with hit=false — the peeker computes
    # locally; no rid, no side effects); /cache/fill is the idempotent
    # owner write-back (rid-deduped exactly like /query, 503 below the
    # floor so stale fills are refused retryably); /peers is the
    # router's topology push after every membership change.
    ("cache_peek", "POST", "/cache/peek",
     f"{_PKG}/serving/fabric.py::_Replica.handle_cache_peek::req",
     (f"{_PKG}/serving/fabric.py::_Replica._peek_owner::out",),
     ("terms", "ranker"),
     ("hit", "generation", "scores", "docs", "error"),
     # error: the 400 body's diagnostic — the peeker acts on the CODE
     ("error",),
     ((200, "success"), (400, "terminal"))),
    ("cache_fill", "POST", "/cache/fill",
     f"{_PKG}/serving/fabric.py::_Replica.handle_cache_fill::req",
     (f"{_PKG}/serving/fabric.py::_Replica._fill_owner::resp",),
     ("rid", "terms", "ranker", "scores", "docs", "generation"),
     ("stored", "replica", "generation", "error", "floor"),
     # replica/generation: operator-facing echo; error/floor: the
     # 400/503 bodies' diagnostics — the filler acts on the CODE
     ("replica", "generation", "error", "floor"),
     ((200, "success"), (400, "terminal"), (503, "retryable"))),
    ("peers", "POST", "/peers",
     f"{_PKG}/serving/fabric.py::_Replica.handle_peers::req",
     (f"{_PKG}/serving/fabric.py::ServingFabric._push_peers",),
     ("peers", "slots"),
     ("ok", "peers", "error"),
     # the push is fire-and-forget: the router acts on the CODE only
     ("ok", "peers", "error"),
     ((200, "success"), (400, "terminal"))),
    ("healthz", "GET", "/healthz",
     f"{_PKG}/obs/export.py::_dispatch",
     (),
     (), (), (),
     ((200, "success"), (503, "retryable"))),
    ("metrics", "GET", "/metrics",
     f"{_PKG}/obs/export.py::_dispatch",
     (),
     (), (), (),
     ((200, "success"),)),
    ("snapshot", "GET", "/snapshot.json",
     f"{_PKG}/obs/export.py::_dispatch",
     (),
     (), (), (),
     ((200, "success"),)),
    # router-side fleet endpoints (ISSUE 19): the router's own exporter
    # serves the SAME obs/export.py dispatcher over the FleetHub, so the
    # merged fleet snapshot/metrics reuse the dispatcher's declared code
    # surface; the scrape path is in-contract via its declared reader —
    # FleetHub's fetch consumes a replica's /snapshot.json (whose
    # "mergeable" payload is opaque raw hub state, not wire keys)
    ("fleet_snapshot", "GET", "/snapshot.json",
     f"{_PKG}/obs/export.py::_dispatch",
     (f"{_PKG}/obs/federation.py::FleetHub._http_fetch",),
     (), (), (),
     ((200, "success"),)),
    ("fleet_metrics", "GET", "/metrics",
     f"{_PKG}/obs/export.py::_dispatch",
     (),
     (), (), (),
     ((200, "success"),)),
    # the dispatcher's catch-alls: "/" is the healthz alias, 404 is the
    # out-of-contract rejection, 500 the handler-exception backstop — the
    # conformance harness allows exactly these beyond a row's own codes
    ("fallback", "GET", "/",
     f"{_PKG}/obs/export.py::_dispatch",
     (),
     (), (), (),
     ((200, "success"), (404, "terminal"), (500, "suspect"),
      (503, "retryable"))),
)

# ---------------------------------------------------------------------------
# Metric-name contract (tier 2, ISSUE 19).
#
# ``METRIC_SCHEMAS`` declares every metric name the repo publishes — the
# run-aggregate namespace (``obs.counter/gauge/histogram``, folded into the
# run summary and trace) and the live-SLO namespace (``MetricsHub``
# counters/gauges/budgets, exported over ``/snapshot.json``/``/metrics``
# and federated across the fleet).  A renamed metric silently breaks every
# downstream reader — dashboards, ``tools/slo_watch.py``, ``trace_diff``
# gates, the federation merge — so the name space is a declared contract,
# not a convention.
#
# Each row is ``(name, kind, unit, sites)``:
#
# - ``name`` may contain ``*`` for template-published families
#   (``fabric_replica*_requests`` is an f-string gauge per replica id);
# - ``kind`` is ``counter`` / ``gauge`` / ``histogram`` / ``slo`` (error
#   budgets; fed by ``observe_request``, not a named publish call);
# - ``unit`` is documentation for operators (board column headers);
# - ``sites`` are the repo-relative modules that publish the name.
#
# The ``metric-name-drift`` check (analysis/rules.py) validates both
# directions: every literal publish call in the package must be covered by
# a row (name AND publishing module), and every row's name must appear in
# every site it claims.  Parsed lexically — keep it a literal.
METRIC_SCHEMAS: tuple = (
    # ---- run-aggregate namespace (obs.counter/gauge/histogram)
    ("degraded", "counter", "count",
     (f"{_PKG}/dataflow/fixpoint.py", f"{_PKG}/models/tfidf.py",
      f"{_PKG}/resilience/elastic.py", f"{_PKG}/resilience/executor.py",
      f"{_PKG}/resilience/process.py", f"{_PKG}/obs/metrics.py")),
    ("*.segment_secs", "histogram", "seconds",
     (f"{_PKG}/dataflow/fixpoint.py",)),
    ("h2d_overlap_frac", "gauge", "fraction",
     (f"{_PKG}/dataflow/ingest.py", f"{_PKG}/obs/metrics.py")),
    ("tfidf.chunks", "counter", "count", (f"{_PKG}/models/tfidf.py",)),
    ("tfidf.chunk_secs", "histogram", "seconds",
     (f"{_PKG}/models/tfidf.py",)),
    ("pagerank.comm_bytes_per_step", "gauge", "bytes",
     (f"{_PKG}/parallel/pagerank_sharded.py",)),
    ("chaos_injections", "counter", "count",
     (f"{_PKG}/resilience/chaos.py",)),
    ("watchdog_fires", "counter", "count",
     (f"{_PKG}/resilience/executor.py",)),
    ("retries", "counter", "count", (f"{_PKG}/resilience/executor.py",)),
    ("backoff_secs", "histogram", "seconds",
     (f"{_PKG}/resilience/executor.py",)),
    ("exhausted", "counter", "count",
     (f"{_PKG}/resilience/executor.py", f"{_PKG}/obs/metrics.py")),
    ("respawns", "counter", "count", (f"{_PKG}/resilience/process.py",)),
    ("fabric_replica*_requests", "gauge", "requests",
     (f"{_PKG}/serving/fabric.py",)),
    # sharded-cache + drain-handoff instruments (ISSUE 20)
    ("cache_peer_hits", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_peer_misses", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_peek_timeouts", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_fills", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_fill_errors", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_breaker_transitions", "counter", "count",
     (f"{_PKG}/serving/fabric.py",)),
    ("cache_peek_s", "histogram", "seconds",
     (f"{_PKG}/serving/fabric.py",)),
    ("fabric_drain_s", "histogram", "seconds",
     (f"{_PKG}/serving/fabric.py",)),
    ("fabric_handoff_s", "histogram", "seconds",
     (f"{_PKG}/serving/fabric.py",)),
    ("segment_commits", "counter", "count",
     (f"{_PKG}/serving/segments.py",)),
    ("segment_orphan_gcs", "counter", "count",
     (f"{_PKG}/serving/segments.py",)),
    ("segment_merges", "counter", "count",
     (f"{_PKG}/serving/segments.py",)),
    ("segment_merge_failures", "counter", "count",
     (f"{_PKG}/serving/segments.py",)),
    ("serve.cache_misses", "counter", "count",
     (f"{_PKG}/serving/server.py",)),
    ("serve.cache_hits", "counter", "count",
     (f"{_PKG}/serving/server.py",)),
    ("serve.batch_errors", "counter", "count",
     (f"{_PKG}/serving/server.py",)),
    ("serve.query_truncated", "counter", "count",
     (f"{_PKG}/serving/server.py",)),
    ("serve.latency_s", "histogram", "seconds",
     (f"{_PKG}/serving/server.py",)),
    ("serve.queue_wait_s", "histogram", "seconds",
     (f"{_PKG}/serving/server.py",)),
    ("checkpoint_saves", "counter", "count",
     (f"{_PKG}/utils/checkpoint.py",)),
    # bench parent's per-label sharded-PageRank comm-volume gauge
    ("owned_scale.comm_bytes.*", "gauge", "bytes", ("bench.py",)),
    ("artifact_saves", "counter", "count",
     (f"{_PKG}/utils/checkpoint.py",)),
    # ---- live-SLO namespace (MetricsHub; federated exactly, ISSUE 19)
    ("serve.requests", "counter", "requests", (f"{_PKG}/obs/metrics.py",)),
    ("serve.ok", "counter", "requests", (f"{_PKG}/obs/metrics.py",)),
    ("serve.errors", "counter", "requests", (f"{_PKG}/obs/metrics.py",)),
    ("chaos.injections", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("chaos.losses", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    # event-kind passthrough counters (ingest_event's kind sets): the
    # publish call is `self.count(kind)`, so the names live in the kind
    # tuples, not in call literals
    ("retry", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("backoff", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("watchdog", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("checkpoint_save", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("serve_start", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("soak_rebuild", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("soak_swap", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("soak_loss_injected", "counter", "count",
     (f"{_PKG}/obs/metrics.py",)),
    ("soak_recovered", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("soak_prior_refresh", "counter", "count",
     (f"{_PKG}/obs/metrics.py",)),
    ("ingest.chunks", "counter", "count", (f"{_PKG}/obs/metrics.py",)),
    ("ingest.tokens", "counter", "tokens", (f"{_PKG}/obs/metrics.py",)),
    # fleet-federation gauges (router-side FleetHub, ISSUE 19)
    ("fed_replicas", "gauge", "count", (f"{_PKG}/obs/federation.py",)),
    ("fed_stale_replicas", "gauge", "count",
     (f"{_PKG}/obs/federation.py",)),
    ("fed_staleness_s_max", "gauge", "seconds",
     (f"{_PKG}/obs/federation.py",)),
    # error budgets (MetricsHub.budgets keys; ErrorBudget instruments)
    ("availability", "slo", "fraction", (f"{_PKG}/obs/metrics.py",)),
    ("latency", "slo", "fraction", (f"{_PKG}/obs/metrics.py",)),
)

# ---------------------------------------------------------------------------
# Autotuning search-space contract (tier 3, ISSUE 16).
#
# ``TUNED_KNOBS`` declares the knob space ``tools/autotune.py`` sweeps and
# the tier-3 ``profile-drift`` check gates: one row per tunable —
# ``(knob name, candidate domain, affected registry entries)``.
#
# - the knob name must appear in ``utils/config.py``'s TUNABLE_DEFAULTS
#   (the single source of hand-picked defaults — domains here deliberately
#   do NOT repeat the default value's meaning; the default is always an
#   implicit member of the search space);
# - the domain is the full candidate grid the tuner enumerates BEFORE the
#   static cost model prunes it (pad-plan/intensity budget violations are
#   discarded unmeasured — the analysis is the search heuristic);
# - affected entries name the ENTRY_POINTS rows whose pad-plan budgets
#   prune this knob's candidates and whose microbenches score survivors.
#
# ``profile-drift`` validates the committed ``tuned_profile_<backend>.json``
# artifacts against this table in both directions (stale knob, missing
# backend stamp, out-of-domain value, declared-but-untuned), and validates
# the table itself against TUNABLE_DEFAULTS and ENTRY_POINTS — the space
# the tuner searches and the knobs the code reads cannot drift apart.
# Parsed lexically — keep it a literal (plain int/float domain values).
TUNED_KNOBS: tuple = (
    # hybrid SpMV dense-head layout: candidates outside the entry's
    # pad_frac ceiling (0.25) on the probe graph are pruned statically
    ("head_coverage", (0.25, 0.5, 0.75),
     ("pagerank_step_hybrid",)),
    ("head_row_width", (64, 128, 256),
     ("pagerank_step_hybrid",)),
    # sort_shuffle bucket padding: wider buckets shrink the reduction but
    # pay pad; the bucket pad fraction is computable without tracing
    ("shuffle_bucket_width", (4, 8, 16),
     ("pagerank_step_sort_shuffle",)),
    # owned-strategy replicated hub-head cap (boundary pad ceiling 0.30)
    ("owned_max_head", (1024, 4096, 8192),
     ("pagerank_sharded_owned",)),
    # staged ingest depths: scheduling-only (results bit-identical), so
    # no pad model prunes them — they ride to measurement unless the
    # paired pack target was already discarded
    ("prefetch", (0, 2, 4),
     ("tfidf_chunk_ingest_carry",)),
    ("pipeline_depth", (0, 2, 4),
     ("tfidf_chunk_ingest_carry",)),
    # streaming chunk re-packing target: 0 (caller chunking as-is) and
    # non-pow2 targets strand pad under the carried grow_chunk_cap pow2
    # policy — provably over the 0.20 drain/carry ceiling, pruned unmeasured
    ("pack_target_tokens", (0, 24000, 100000, 131072, 262144),
     ("tfidf_chunk_drain", "tfidf_chunk_ingest_carry")),
    # serving batch cap (query-batch pad ceiling 0.30)
    ("max_batch", (4, 8, 16),
     ("tfidf_score_query_batch",)),
    # impacted-list scoring bucket layout (impacted pad ceiling 0.62)
    ("impact_bucket_width", (4, 8, 16),
     ("tfidf_score_impacted_batch",)),
    ("impact_warm_buckets", (4096, 8192, 16384),
     ("tfidf_score_impacted_batch",)),
)

# ``--tier all`` runs two analyzers (semantic + cost) over the same
# registry in one process; building an entry — graph synthesis, mesh
# construction, partitioning per shrink-chain device count — is the
# expensive part of a lint pass, and the Traceable is immutable, so build
# once per process.  (Each tier still traces under its own config context:
# tier 2 under x64, tier 3 under production dtypes.)  Failures are NOT
# cached: a broken entry must re-raise in every tier that looks at it.
_BUILD_CACHE: "dict[EntryPoint, Traceable]" = {}


def build_traceable(ep: "EntryPoint") -> "Traceable":
    t = _BUILD_CACHE.get(ep)
    if t is None:
        t = _BUILD_CACHE[ep] = ep.build()
    return t


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _f32(shape):
    import numpy as np

    return _sds(shape, np.float32)


def _i32(shape):
    import numpy as np

    return _sds(shape, np.int32)


def _device_graph_spec(n: int, e: int):
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.ops.pagerank import DeviceGraph

    return DeviceGraph(
        src=_i32((e,)),
        dst=_i32((e,)),
        inv_outdeg=_f32((n,)),
        dangling=_f32((n,)),
        has_outlinks=_f32((n,)),
        indptr=_sds((n + 1,), np.int32),
    )


# ----------------------------------------------------------------- pagerank


def _build_pagerank_scan() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e = 64, 256
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    run = ops.make_pagerank_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.pagerank_step,
    )


def _build_pagerank_while_cumsum() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e = 64, 256
    cfg = PageRankConfig(iterations=8, tol=1e-6, spmv_impl="cumsum")
    run = ops.make_pagerank_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64-tol", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.make_pagerank_runner,
    )


def _shrink_chain(d0: int) -> list[int]:
    """The device counts the elastic rung can rebuild onto from ``d0``:
    the power-of-two shrink chain d0, d0/2, ..., 1 (resilience/elastic.py).
    Every sharded entry traces each of them, so the semantic gates
    (promotion, transfer census, collective budget) hold for the shrunk
    meshes a degraded run executes on — not only the healthy shape."""
    chain = []
    d = d0
    while d >= 1:
        chain.append(d)
        d //= 2
    return chain


def _sharded_pagerank_traceable(strategy: str) -> Traceable:
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded as ps,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        NODES_AXIS,
        make_mesh,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    graph = synthetic_powerlaw(64, 256, seed=1)
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    runners: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, NODES_AXIS)
        sg = ps.partition_graph(graph, d, strategy=strategy)
        runners[d] = ps.make_sharded_runner(sg, cfg, mesh)
        head = (
            (_i32(sg.head_src.shape), _i32(sg.head_node.shape))
            if strategy == "hybrid" else ()
        )
        args = (
            _f32((sg.n_pad,)),
            _i32(sg.src.shape),
            _i32(sg.dst.shape),
            _f32(sg.valid.shape),
            _i32(sg.local_indptr.shape),
            *head,
            _f32((sg.n_pad,)),
            _f32((sg.n_pad,)),
            _f32((sg.n_pad,)),
        )
        variants.append((f"{strategy}-d{d}", args))

    def dispatch(ranks, src, *rest):
        # per-device-count runners: the edge arrays are [d, e_dev], so the
        # leading dim names which compiled program this variant exercises
        return runners[src.shape[0]](ranks, src, *rest)

    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ps.make_sharded_runner,
    )


def _sharded_pad_plan(strategy: str):
    """Static padding-waste plan points for a sharded entry: pad_frac of
    the partition *plan* (parallel.pagerank_sharded.plan_partition — no
    arrays materialized, no dispatch) on the registry's trace graph, one
    point per device count on the elastic shrink chain."""

    def plan() -> list[tuple[str, float]]:
        import jax

        from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
            synthetic_powerlaw,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
            plan_partition,
        )

        graph = synthetic_powerlaw(64, 256, seed=1)
        return [
            (
                f"{strategy}-d{d}",
                plan_partition(graph, d, strategy=strategy).pad_frac,
            )
            for d in _shrink_chain(min(4, len(jax.devices())))
        ]

    return plan


def _chunk_pad_plan() -> "list[tuple[str, float]]":
    """Static padding waste of the streaming ingest's grow_chunk_cap
    policy over the declared raw-token matrix."""
    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        stream_pad_plan,
    )

    return stream_pad_plan(CHUNK_TOKEN_MATRIX)


def _layout_device_graph_spec(layout: str):
    """DeviceGraph spec INCLUDING the static SpMV layout arrays: the
    layout shapes are graph-dependent, so they come from a real host
    build on the registry's trace graph (seed 1 — the same graph the
    sharded entries partition)."""
    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops

    graph = synthetic_powerlaw(64, 256, seed=1)
    # production (models.pagerank.put_graph_for) skips the raw edge
    # arrays for layout-backed impls — mirror that in the traced spec
    base = _device_graph_spec(graph.n_nodes, graph.n_edges)._replace(
        src=_i32((0,)), dst=_i32((0,)), indptr=_i32((0,))
    )
    if layout == "hybrid":
        hl = ops.build_hybrid_layout(graph)
        hybrid = ops.HybridLayout(
            head_ids=_i32(hl.head_ids.shape),
            head_src=_i32(hl.head_src.shape),
            head_row_node=_i32(hl.head_row_node.shape),
            tail_src=_i32(hl.tail_src.shape),
            tail_dst=_i32(hl.tail_dst.shape),
            tail_indptr=_i32(hl.tail_indptr.shape),
        )
        return graph.n_nodes, base._replace(hybrid=hybrid)
    bucket_src, bucket_node, _bucket_w = ops.build_shuffle_layout(graph)
    shuffle = ops.ShuffleLayout(
        bucket_src=_i32(bucket_src.shape), bucket_node=_i32(bucket_node.shape)
    )
    return graph.n_nodes, base._replace(shuffle=shuffle)


def _build_pagerank_hybrid() -> Traceable:
    """The degree-aware hybrid SpMV fixpoint runner: dense MXU head rows +
    segment tail (ops.spmv_hybrid), traced with the real layout shapes."""
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, dg = _layout_device_graph_spec("hybrid")
    cfg = PageRankConfig(iterations=4, dangling="redistribute",
                         init="uniform", spmv_impl="hybrid")
    run = ops.make_pagerank_runner(n, cfg)
    return Traceable(
        fn=run,
        variants=[("n64-hybrid", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.spmv_hybrid,
    )


def _build_pagerank_sort_shuffle() -> Traceable:
    """The sort-based static-shuffle SpMV fixpoint runner: fixed-width
    dst buckets, pure reshape->reduce contribution side."""
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, dg = _layout_device_graph_spec("sort_shuffle")
    cfg = PageRankConfig(iterations=4, dangling="redistribute",
                         init="uniform", spmv_impl="sort_shuffle")
    run = ops.make_pagerank_runner(n, cfg)
    return Traceable(
        fn=run,
        variants=[("n64-shuffle", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.spmv_sort_shuffle,
    )


def _build_pagerank_rowsum_pallas() -> Traceable:
    """The hybrid head's Pallas row-reduction kernel in interpret mode —
    tier-2/3 coverage of the on-chip dense reduce without a chip (the
    production hybrid path only takes it on a real TPU backend)."""
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import (
        pallas_kernels as pk,
    )

    fn = functools.partial(pk.rowsum_pallas, interpret=True)
    return Traceable(
        fn=fn,
        variants=[("r2048xw128", (_f32((2048, 128)),))],
        anchor=pk.rowsum_pallas,
    )


def _build_pagerank_sharded_owned() -> Traceable:
    """The owned-slices strategy (ISSUE 15): boundary butterfly + one
    head psum, 4-leaf donated carry — its own builder because the operand
    structure (lookup-index edge arrays, boundary pack indices, split
    tail/head state vectors) differs from every replicated strategy."""
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded as ps,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        NODES_AXIS,
        make_mesh,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    graph = synthetic_powerlaw(64, 256, seed=1)
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    runners: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, NODES_AXIS)
        sg = ps.partition_graph(graph, d, strategy="owned")
        sh = sg.owned
        runners[d] = ps.make_sharded_runner(sg, cfg, mesh)
        carry = (_f32((sh.n_pad,)), _f32((sh.h_pad,)), _f32((d,)), _f32(()))
        args = (
            carry,
            _i32(sh.tail_src_idx.shape), _i32(sh.tail_dst.shape),
            _f32(sh.tail_w.shape),
            _i32(sh.head_src_idx.shape), _i32(sh.head_slot.shape),
            _f32(sh.head_w.shape),
            _i32(sh.out_idx.shape),
            _f32((sh.n_pad,)), _f32((sh.n_pad,)),
            _f32((sh.h_pad,)), _f32((sh.h_pad,)),
            _f32((sh.n_pad,)), _f32((sh.h_pad,)),
        )
        variants.append((f"owned-d{d}", args))

    def dispatch(carry, tsrc, *rest):
        # the edge arrays are [d, e_dev]: the leading dim names which
        # compiled program this variant exercises
        return runners[tsrc.shape[0]](carry, tsrc, *rest)

    # The donation verifier lowers donate_fn with variants[0]'s args —
    # order the chain SMALLEST-first so that is the d=1 program: the CPU
    # backend's multi-device SPMD lowering drops input/output aliasing
    # entirely (0 aliased buffers at d>1 regardless of donate_argnums),
    # so the single-device lowering is the one place the donate_argnums
    # contract is statically checkable off-TPU.
    variants.reverse()
    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ps.make_sharded_runner,
        donate_fn=runners[min(runners)],
    )


def _owned_pad_plan():
    """Both padding gauges of the owned plan on the trace graph, one
    point per shrink-chain device count: the edge-slot pad_frac (same
    gauge as every strategy) AND the boundary-buffer pad fraction (the
    'pad ceilings over boundary buffers' the ISSUE budgets).  d=1 has no
    exchange, so no boundary point (its 1-slot placeholder buffer is
    100% padding by construction and gauges nothing)."""

    def plan() -> list[tuple[str, float]]:
        import jax

        from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
            synthetic_powerlaw,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.parallel.pagerank_sharded import (
            plan_partition,
        )

        graph = synthetic_powerlaw(64, 256, seed=1)
        points: list[tuple[str, float]] = []
        for d in _shrink_chain(min(4, len(jax.devices()))):
            p = plan_partition(graph, d, strategy="owned")
            points.append((f"owned-d{d}", p.pad_frac))
            if d > 1:
                points.append(
                    (f"owned-d{d}-boundary", p.owned.boundary_pad_frac)
                )
        return points

    return plan


def _owned_pair_variants(kind: str):
    """Shared builder half of the owned HITS/CC entries: per shrink-chain
    device count, the (forward, reverse) owned shards and the compiled
    runner, plus that count's abstract operand specs."""
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import (
        synthetic_powerlaw,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        workloads_sharded as ws,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        NODES_AXIS,
        make_mesh,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        ComponentsConfig,
        HitsConfig,
    )

    graph = synthetic_powerlaw(64, 256, seed=1)
    runners: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, NODES_AXIS)
        sf, sr = ws.build_owned_pair(graph, d, "float32")
        fe = (_i32(sf.tail_src_idx.shape), _i32(sf.tail_dst.shape),
              _f32(sf.tail_w.shape), _i32(sf.out_idx.shape))
        re_ = (_i32(sr.tail_src_idx.shape), _i32(sr.tail_dst.shape),
               _f32(sr.tail_w.shape), _i32(sr.out_idx.shape))
        if kind == "hits":
            runners[d] = ws.make_hits_sharded_runner(
                sf, sr, HitsConfig(iterations=4, tol=0.0), mesh
            )
            carry = (_f32((sf.n_pad,)), _f32((sf.n_pad,)))
            args = (carry, *fe, *re_)
        else:
            runners[d] = ws.make_components_sharded_runner(
                sf, sr, ComponentsConfig(iterations=8), mesh
            )
            # the CC runner takes (fsrc, fdst, rsrc, rdst, fout, rout)
            args = (_i32((sf.n_pad,)), fe[0], fe[1], re_[0], re_[1],
                    fe[3], re_[3])
        variants.append((f"{kind}-owned-d{d}", args))

    def dispatch(carry, head, *rest):
        return runners[head.shape[0]](carry, head, *rest)

    return dispatch, variants


def _build_hits_sharded_owned() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        workloads_sharded as ws,
    )

    dispatch, variants = _owned_pair_variants("hits")
    return Traceable(fn=dispatch, variants=variants,
                     anchor=ws.make_hits_sharded_runner)


def _build_components_sharded_owned() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        workloads_sharded as ws,
    )

    dispatch, variants = _owned_pair_variants("cc")
    return Traceable(fn=dispatch, variants=variants,
                     anchor=ws.make_components_sharded_runner)


def _build_pagerank_sharded_edges() -> Traceable:
    return _sharded_pagerank_traceable("edges")


def _build_pagerank_sharded_hybrid() -> Traceable:
    return _sharded_pagerank_traceable("hybrid")


def _build_pagerank_sharded_nodes_balanced() -> Traceable:
    return _sharded_pagerank_traceable("nodes_balanced")


def _build_pagerank_sharded_src() -> Traceable:
    return _sharded_pagerank_traceable("src")


# -------------------------------------------------------------------- tfidf


def _build_tfidf_batch() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import IdfMode, TfMode

    cap, n_docs, vocab = 4096, 16, 1 << 10
    fn = functools.partial(
        ops.tfidf_pipeline,
        n_docs=n_docs,
        vocab=vocab,
        tf_mode=TfMode.FREQ,
        idf_mode=IdfMode.SMOOTH,
        l2_normalize=True,
    )
    return Traceable(
        fn=fn,
        variants=[("batch4k", (_i32((cap,)), _i32((cap,)), _i32((n_docs,))))],
        anchor=ops.tfidf_pipeline,
    )


def _build_tfidf_chunk_drain() -> Traceable:
    import functools
    import logging

    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import grow_chunk_cap
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    # Run the declared raw-token matrix through the real streaming padding
    # policy, exactly as run_tfidf_streaming would: distinct caps == distinct
    # compiles of the chunk kernel.  The recorder's cap-bump log lines are
    # production telemetry — mute them for a lint pass.
    log = logging.getLogger("pr_tfidf_tpu")
    was_disabled = log.disabled
    log.disabled = True
    try:
        metrics = MetricsRecorder()
        cap = 0
        caps: list[int] = []
        for raw in CHUNK_TOKEN_MATRIX:
            cap, _ = grow_chunk_cap(raw, cap, metrics)
            caps.append(cap)
    finally:
        log.disabled = was_disabled
    variants = []
    for raw, cap in zip(CHUNK_TOKEN_MATRIX, caps):
        variants.append(
            (
                f"tokens{raw}",
                (_i32((cap,)), _i32((cap,)), _sds((cap,), np.bool_)),
            )
        )
    fn = functools.partial(ops.chunk_counts, vocab=1 << 10)
    return Traceable(fn=fn, variants=variants, anchor=ops.chunk_counts)


def _build_pagerank_pallas() -> Traceable:
    """The spmv_impl='pallas' fixpoint runner, traced in interpret mode.

    Mosaic only compiles on real TPUs, but ``_spmv`` flips the kernel to
    the Pallas *interpreter* whenever the trace-time backend is not TPU —
    so on the analyzer's pinned CPU backend the full runner (gather +
    pallas_call prefix sum + CSR diff + damping epilogue) traces into one
    jaxpr and every tier-2/tier-3 gate (promotion, transfer census,
    intensity, donation) covers the Pallas path too, chip or no chip."""
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import (
        pallas_kernels as pk,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e = 64, 256
    cfg = PageRankConfig(iterations=4, dangling="redistribute",
                         init="uniform", spmv_impl="pallas")
    run = ops.make_pagerank_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64-pallas", (dg, _f32((n,)), _f32((n,))))],
        anchor=pk.spmv_pallas,
    )


def _build_tfidf_chunk_ingest_carry() -> Traceable:
    """The production streaming kernel: chunk counts + the device-resident
    donated DF carry (ops.chunk_counts_carry), shape matrix through the
    real grow_chunk_cap policy exactly like the legacy drain entry."""
    import functools
    import logging

    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import grow_chunk_cap
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    vocab = 1 << 10
    log = logging.getLogger("pr_tfidf_tpu")
    was_disabled = log.disabled
    log.disabled = True
    try:
        metrics = MetricsRecorder()
        cap = 0
        caps: list[int] = []
        for raw in CHUNK_TOKEN_MATRIX:
            cap, _ = grow_chunk_cap(raw, cap, metrics)
            caps.append(cap)
    finally:
        log.disabled = was_disabled
    variants = []
    for raw, cap in zip(CHUNK_TOKEN_MATRIX, caps):
        variants.append(
            (
                f"tokens{raw}",
                (_i32((cap,)), _i32((cap,)), _sds((cap,), np.bool_),
                 _f32((vocab,))),
            )
        )
    fn = functools.partial(ops.chunk_counts_carry, vocab=vocab)
    return Traceable(
        fn=fn,
        variants=variants,
        anchor=ops.chunk_counts_carry,
        donate_fn=ops.chunk_counts_carry,
        donate_kwargs={"vocab": vocab},
    )


def _build_tfidf_sharded_ingest() -> Traceable:
    import jax
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        tfidf_sharded as ts,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS,
        make_mesh,
    )

    cap, vocab = 2048, 1 << 10
    kernels: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, DATA_AXIS)
        kernels[d] = ts.make_sharded_counts_kernel(mesh, vocab)
        args = (
            _i32((d, cap)),
            _i32((d, cap)),
            _sds((d, cap), np.bool_),
        )
        variants.append((f"d{d}-cap{cap}", args))

    def dispatch(doc_ids, term_ids, valid):
        return kernels[doc_ids.shape[0]](doc_ids, term_ids, valid)

    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ts.make_sharded_counts_kernel,
    )


def _build_tfidf_finalize() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfMode

    nnz, n_docs = 4096, 16
    fn = functools.partial(
        ops.finalize_weights, n_docs=n_docs, tf_mode=TfMode.FREQ, l2_normalize=True
    )
    return Traceable(
        fn=fn,
        variants=[
            ("nnz4k", (_i32((nnz,)), _f32((nnz,)), _i32((n_docs,)), _f32((nnz,))))
        ],
        anchor=ops.finalize_weights,
    )


def _build_tfidf_score_query() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops

    cap, n_docs, vocab, k = 2048, 32, 1 << 10, 8
    result = ops.TfidfResult(
        doc=_i32((cap,)),
        term=_i32((cap,)),
        weight=_f32((cap,)),
        n_pairs=_i32(()),
        valid=_f32((cap,)),
        idf=_f32((vocab,)),
        df=_f32((vocab,)),
    )
    fn = functools.partial(ops.score_query, n_docs=n_docs, k=k)
    return Traceable(
        fn=fn,
        variants=[("top8", (result, _f32((vocab,))))],
        anchor=ops.score_query,
    )


# Raw micro-batch sizes the serving drain loop sees in production (mixed
# single requests, partial batches, a full batch): run through the REAL
# serving padding policy (serving.server.batch_cap — grow_chunk_cap with
# min_bits=0) they must collapse to the power-of-two matrix the server
# warms, or the recompile gate fires — "zero per-request recompiles" as a
# statically checked contract, not a hope.
SERVE_BATCH_MATRIX = (1, 2, 3, 5, 7, 8, 11, 16)
SERVE_MAX_BATCH = 16


def _serve_pad_plan() -> "list[tuple[str, float]]":
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        serve_pad_plan,
    )

    return serve_pad_plan(SERVE_BATCH_MATRIX, SERVE_MAX_BATCH)


def _build_tfidf_score_query_batch() -> Traceable:
    """The warm serving path's batched scorer (serving/server.py drives
    it): one compiled program per padded batch cap, sparse [B, Q] queries,
    top-k fused on device."""
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        batch_cap,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    cap, n_docs, vocab, k, q = 2048, 32, 1 << 10, 8, 16
    metrics = MetricsRecorder()
    variants = []
    for b in SERVE_BATCH_MATRIX:
        bc = batch_cap(b, SERVE_MAX_BATCH, metrics)
        variants.append(
            (
                f"batch{b}",
                (
                    _i32((cap,)), _i32((cap,)), _f32((cap,)), _f32((cap,)),
                    _i32((bc, q)), _f32((bc, q)), _f32((bc, q)),
                    _f32((n_docs,)),
                ),
            )
        )
    fn = functools.partial(
        ops.score_query_batch, n_docs=n_docs, vocab=vocab, k=k,
        use_prior=True,
    )
    return Traceable(fn=fn, variants=variants, anchor=ops.score_query_batch)


# Raw per-batch bucket counts the impacted-list planner produces in
# production (Σ ceil(run/W) over the batch's query terms): run through the
# REAL carried grow_chunk_cap policy (serving.server.impacted_pad_plan /
# the planner's cap state) they must collapse to a handful of pow2 caps —
# the bucket axis of the impacted serving shape matrix.
IMPACT_BUCKET_MATRIX = (23, 40, 150, 900, 64)


def _impacted_pad_plan() -> "list[tuple[str, float]]":
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        impacted_pad_plan,
    )

    return impacted_pad_plan(IMPACT_BUCKET_MATRIX)


def _build_tfidf_score_impacted_batch() -> Traceable:
    """The latency-shaped serving scorer (ISSUE 13, serving/server.py
    drives it): CSC-by-term posting runs padded into fixed-width buckets,
    one reshape→gather→scatter-add program per (batch cap, bucket cap)
    point — work ∝ the batch's query terms' posting runs, not nnz."""
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import (
        grow_chunk_cap,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.serving.server import (
        IMPACT_MIN_BUCKET_BITS,
        batch_cap,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    nnz, n_docs, k, w = 2048, 32, 8, 8
    metrics = MetricsRecorder()
    # the bucket caps the declared raw counts produce under the carried
    # pow2 policy — the same state discipline the serving planner keeps
    bcap = 0
    bcaps = []
    for raw in IMPACT_BUCKET_MATRIX:
        bcap, _ = grow_chunk_cap(max(raw, 1), bcap, metrics,
                                 min_bits=IMPACT_MIN_BUCKET_BITS)
        bcaps.append(bcap)
    variants = []
    seen: set = set()
    for b, bc in zip(SERVE_BATCH_MATRIX, bcaps + bcaps[: max(
            0, len(SERVE_BATCH_MATRIX) - len(bcaps))]):
        cap = batch_cap(b, SERVE_MAX_BATCH, metrics)
        if (cap, bc) in seen:
            continue
        seen.add((cap, bc))
        variants.append(
            (
                f"b{cap}-c{bc}",
                (
                    _i32((cap,)),  # batch marker: dispatch reads batch here
                    _i32((nnz,)), _f32((nnz,)),
                    _i32((bc,)), _i32((bc,)), _i32((bc,)), _f32((bc,)),
                    _f32((n_docs,)),
                ),
            )
        )

    fn = functools.partial(
        ops.score_impacted_batch, n_docs=n_docs, bucket_width=w, k=k,
        use_prior=True,
    )

    def dispatch(marker, doc, weight, bs, bl, br, bqw, prior):
        # the padded batch cap is a static of the inner jit; the marker
        # array's length names which compiled program a variant exercises
        return fn(doc, weight, bs, bl, br, bqw, prior,
                  batch=marker.shape[0])

    # donate=() rides the default surface: the dispatch wrapper lowers
    # whole (marker included) and must record ZERO aliased inputs
    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ops.score_impacted_batch,
    )


def _build_tfidf_topk_merge() -> Traceable:
    """Device-side per-segment top-k merge (serving across live delta
    segments): concat + re-rank + id globalization in one fused program;
    one compile per (segment count, batch cap) pair."""
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops

    b, k = 8, 8
    fn = functools.partial(ops.topk_merge, k=k)
    variants = []
    for s in (2, 3):
        scores = tuple(_f32((b, k)) for _ in range(s))
        ids = tuple(_i32((b, k)) for _ in range(s))
        bases = tuple(_i32(()) for _ in range(s))
        variants.append((f"s{s}", (scores, ids, bases)))
    return Traceable(fn=fn, variants=variants, anchor=ops.topk_merge)


# ---------------------------------------------------- dataflow workloads


def _build_ppr_batch() -> Traceable:
    """Batched personalized PageRank: the vmapped fixpoint runner with a
    [B, n] donated rank carry and [B, n] teleport matrix."""
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import ppr
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e, b = 64, 256, 4
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    run = ppr.make_ppr_batch_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("b4-n64", (dg, _f32((b, n)), _f32((b, n))))],
        anchor=ppr.make_ppr_batch_runner,
    )


def _build_hits() -> Traceable:
    """HITS: two interleaved SpMV passes (sorted dst combine + unsorted
    src combine) with per-step max normalization, [2, n] donated carry."""
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import hits
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import HitsConfig

    n, e = 64, 256
    run = hits.make_hits_runner(n, HitsConfig(iterations=4, tol=0.0))
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64", (dg, _f32((2, n))))],
        anchor=hits.hits_step,
    )


def _build_components() -> Traceable:
    """Connected components: min-label propagation to fixpoint (while
    loop, changed-label-count delta), int32 donated label carry."""
    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import components
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
        ComponentsConfig,
    )

    n, e = 64, 256
    run = components.make_components_runner(n, ComponentsConfig(iterations=8))
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64", (dg, _i32((n,))))],
        anchor=components.label_step,
    )


def _build_bm25_weights() -> Traceable:
    """BM25 re-weighting of the postings COO: two gathers + elementwise
    math, one compile per nnz shape (index build time)."""
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.dataflow import bm25

    nnz, n_docs, vocab = 4096, 16, 1 << 10
    fn = functools.partial(bm25.bm25_weights, n_docs=n_docs, k1=1.5, b=0.75)
    return Traceable(
        fn=fn,
        variants=[
            ("nnz4k", (_i32((nnz,)), _i32((nnz,)), _f32((nnz,)),
                       _i32((n_docs,)), _f32((vocab,))))
        ],
        anchor=bm25.bm25_weights,
    )


# ------------------------------------------------------------- the registry

ENTRY_POINTS: tuple[EntryPoint, ...] = (
    EntryPoint(
        name="pagerank_step",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_scan,
        watch=(f"{_PKG}/dataflow/fixpoint.py",),
        # iterate-to-fixpoint runner: the rank carry (argnum 1 of
        # run(dg, ranks0, e)) is donated — verified against the lowered
        # aliasing by the tier-3 donation check
        donate=(1,),
        intensity_floor=0.05,  # static model measures 0.066
    ),
    EntryPoint(
        name="pagerank_step_tol_cumsum",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_while_cumsum,
        watch=(f"{_PKG}/dataflow/fixpoint.py",),
        donate=(1,),
        intensity_floor=0.045,  # static model measures 0.054
    ),
    EntryPoint(
        name="pagerank_step_pallas",
        module=f"{_PKG}/ops/pallas_kernels.py",
        build=_build_pagerank_pallas,
        # the runner composes ops/pagerank.py machinery around the kernel
        watch=(f"{_PKG}/ops/pagerank.py", f"{_PKG}/dataflow/fixpoint.py"),
        donate=(1,),
        intensity_floor=0.04,  # static model measures 0.050
    ),
    EntryPoint(
        name="pagerank_step_hybrid",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_hybrid,
        watch=(f"{_PKG}/dataflow/fixpoint.py",),
        donate=(1,),
        intensity_floor=0.05,  # static model measures 0.075
    ),
    EntryPoint(
        name="pagerank_step_sort_shuffle",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_sort_shuffle,
        watch=(f"{_PKG}/dataflow/fixpoint.py",),
        donate=(1,),
        intensity_floor=0.05,  # static model measures 0.072
    ),
    EntryPoint(
        name="pagerank_rowsum_pallas",
        module=f"{_PKG}/ops/pallas_kernels.py",
        build=_build_pagerank_rowsum_pallas,
        # the hybrid impl routes its dense head through this kernel on a
        # real TPU backend (ops.pagerank.hybrid_rowsum)
        watch=(f"{_PKG}/ops/pagerank.py",),
        # the model charges the pre-kernel pad copy as extra HBM traffic,
        # so the static intensity is 0.050 (2 flops per element over ~2.5
        # array passes), not the kernel's own 0.25
        intensity_floor=0.045,
    ),
    EntryPoint(
        name="pagerank_sharded_edges",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_edges,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # one psum per iteration: the contribs combine (replicated state
        # needs no dangling-mass or delta collective)
        collective_budget=1,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        # equal contiguous edge slices: padding is only the ceil remainder
        pad_plan=_sharded_pad_plan("edges"),
        pad_frac_ceiling=0.05,
        intensity_floor=0.035,  # static model: 0.047 at d=1 (worst)
    ),
    EntryPoint(
        name="pagerank_sharded_nodes_balanced",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_nodes_balanced,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # all_gather(weighted ranks) + psum(dangling mass) + psum(delta)
        collective_budget=3,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        # RATCHETED with the hybrid/power-law PR: the optimal min-max
        # boundary search (plan_partition) brought the trace-graph worst
        # point from 0.47 to 0.10 at d=4 (and the 8-device dryrun plan
        # from 0.61 to 0.47, its node-granularity floor — one hub's
        # in-edge run cannot split across devices in this layout; the
        # 'hybrid' strategy exists to go below that floor).
        pad_plan=_sharded_pad_plan("nodes_balanced"),
        pad_frac_ceiling=0.25,
        intensity_floor=0.035,  # static model: 0.045 at d=4 (worst)
    ),
    EntryPoint(
        name="pagerank_sharded_hybrid",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_hybrid,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # one psum combines head + tail partials (replicated state needs
        # no dangling-mass or delta collective)
        collective_budget=1,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        # row/edge-granular splits: only dense-row sentinels and two ceil
        # remainders pad (0.21 at d=4 on the hub-dense 256-edge trace
        # graph; 0.0001 at web-Google scale, where the ROADMAP "pad_frac
        # below 0.25 for the balanced strategies" goal is measured)
        pad_plan=_sharded_pad_plan("hybrid"),
        pad_frac_ceiling=0.25,
        intensity_floor=0.04,  # static model: 0.052 at d=4 (worst)
    ),
    EntryPoint(
        name="pagerank_sharded_owned",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_owned,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/ops/boundary.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # THE owned collective contract (ISSUE 15 acceptance): log2(d)
        # ppermute rounds of the boundary butterfly + exactly ONE psum —
        # the [H_pad+2] head combine whose spare slots carry the dangling
        # mass and the lagged delta, so neither adds a collective.  Worst
        # traced point is d=4: 2 ppermutes + 1 psum = 3.
        collective_budget=3,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        # two gauges per chain point: edge-slot pad_frac (ceil remainders
        # only — both edge classes split at edge granularity) and the
        # boundary-buffer pad fraction (pow2 width over max |S_j|; worst
        # trace-graph point 0.22 at d=2)
        pad_plan=_owned_pad_plan(),
        pad_frac_ceiling=0.30,
        # the 4-leaf owned carry (tail slice, replicated head, dslot,
        # gdelta) is donated at argnum 0 — per-chip state being O(n/d) is
        # the strategy's reason to exist, so the carry may not double
        donate=(0,),
        intensity_floor=0.03,  # static model: 0.042 at d=4 (worst)
    ),
    EntryPoint(
        name="pagerank_sharded_src",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_src,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # reduce-scatter exchange + psum(dangling mass) + psum(delta)
        collective_budget=3,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        # push layout: out-degree is the bounded axis, padding stays small
        pad_plan=_sharded_pad_plan("src"),
        pad_frac_ceiling=0.25,
        intensity_floor=0.03,  # static model: 0.040 at d=4 (worst)
    ),
    EntryPoint(
        name="hits_sharded_owned",
        module=f"{_PKG}/parallel/workloads_sharded.py",
        build=_build_hits_sharded_owned,
        watch=(
            f"{_PKG}/ops/boundary.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # two boundary butterflies (2·log2(d) ppermutes) + two pmax norms
        # + the convergence psum: 7 at the traced d=4 worst
        collective_budget=7,
        max_compiles=3,
        intensity_floor=0.03,
    ),
    EntryPoint(
        name="components_sharded_owned",
        module=f"{_PKG}/parallel/workloads_sharded.py",
        build=_build_components_sharded_owned,
        watch=(
            f"{_PKG}/ops/boundary.py",
            f"{_PKG}/dataflow/fixpoint.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # two boundary butterflies + the changed-count psum: 5 at d=4
        collective_budget=5,
        max_compiles=3,
        intensity_floor=0.01,
    ),
    EntryPoint(
        name="tfidf_batch_pipeline",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_batch,
        intensity_floor=0.09,  # static model measures 0.109
    ),
    EntryPoint(
        name="tfidf_chunk_drain",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_chunk_drain,
        # the shape matrix runs through models/tfidf.py grow_chunk_cap —
        # a policy change there must re-verify this contract
        watch=(f"{_PKG}/models/tfidf.py", f"{_PKG}/dataflow/ingest.py"),
        # The doubling cap policy may legally produce a handful of buckets
        # over a whole stream; the declared matrix must collapse to <= 3.
        max_compiles=3,
        # stream-aggregate padding of the doubling-cap policy (~0.13 on
        # the declared matrix; doubling bounds the worst steady state at
        # <0.5 but the declared workload must stay far under that)
        pad_plan=_chunk_pad_plan,
        pad_frac_ceiling=0.20,
        intensity_floor=0.25,  # static model: 0.265 at the smallest cap
    ),
    EntryPoint(
        name="tfidf_chunk_ingest_carry",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_chunk_ingest_carry,
        watch=(f"{_PKG}/models/tfidf.py", f"{_PKG}/dataflow/ingest.py"),
        max_compiles=3,
        pad_plan=_chunk_pad_plan,
        pad_frac_ceiling=0.20,
        # the ingest carry: the device DF accumulator (argnum 3) must be
        # donated so XLA updates it in place every chunk
        donate=(3,),
        intensity_floor=0.25,  # static model: 0.265 at the smallest cap
    ),
    EntryPoint(
        name="tfidf_sharded_ingest",
        module=f"{_PKG}/parallel/tfidf_sharded.py",
        build=_build_tfidf_sharded_ingest,
        watch=(
            f"{_PKG}/ops/tfidf.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
            # the host loop is the staged pipeline now (ISSUE 10): a
            # change to the staging/commit discipline must re-verify the
            # sharded contracts (collective budget, shrink-chain compiles)
            f"{_PKG}/dataflow/ingest.py",
        ),
        axes=("data",),
        # exactly the DF psum — the one reduceByKey of the ingest step
        collective_budget=1,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
        intensity_floor=0.15,  # static model measures 0.180
    ),
    EntryPoint(
        name="tfidf_finalize",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_finalize,
        intensity_floor=0.045,  # static model measures 0.061
    ),
    EntryPoint(
        name="tfidf_score_query",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_score_query,
        intensity_floor=0.04,  # static model measures 0.060
    ),
    EntryPoint(
        name="dataflow_ppr_batch",
        module=f"{_PKG}/dataflow/ppr.py",
        build=_build_ppr_batch,
        # the step math and the iterate skeleton live under these
        watch=(f"{_PKG}/ops/pagerank.py", f"{_PKG}/dataflow/fixpoint.py"),
        # the [B, n] rank carry (argnum 1) is donated, same contract as
        # the single-query runner
        donate=(1,),
        intensity_floor=0.06,  # static model measures 0.086 (b=4)
    ),
    EntryPoint(
        name="dataflow_hits",
        module=f"{_PKG}/dataflow/hits.py",
        build=_build_hits,
        watch=(f"{_PKG}/dataflow/combine.py", f"{_PKG}/dataflow/fixpoint.py"),
        donate=(1,),
        intensity_floor=0.04,  # static model measures 0.049
    ),
    EntryPoint(
        name="dataflow_components",
        module=f"{_PKG}/dataflow/components.py",
        build=_build_components,
        watch=(f"{_PKG}/dataflow/combine.py", f"{_PKG}/dataflow/fixpoint.py"),
        donate=(1,),
        intensity_floor=0.04,  # static model measures 0.052
    ),
    EntryPoint(
        name="dataflow_bm25_weights",
        module=f"{_PKG}/dataflow/bm25.py",
        build=_build_bm25_weights,
        # pure re-weighting pass: gathers + elementwise over the COO
        intensity_floor=0.10,  # static model measures 0.122
    ),
    EntryPoint(
        name="tfidf_score_query_batch",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_score_query_batch,
        # the padding policy lives in serving/server.py (batch_cap over
        # models/tfidf.py's grow_chunk_cap): a change to either must
        # re-verify the zero-per-request-recompile contract
        watch=(
            f"{_PKG}/serving/server.py",
            f"{_PKG}/models/tfidf.py",
            f"{_PKG}/dataflow/ingest.py",
        ),
        # one compile per padded batch cap: {1, 2, 4, 8, 16} at
        # max_batch 16 — the full warm set; anything beyond means an
        # unpadded batch shape reached jit
        max_compiles=5,
        pad_plan=_serve_pad_plan,
        # the declared raw-batch matrix fills 53 of 63 dispatched slots
        # (pad_frac ~0.159); the worst steady state of pow2 padding is
        # < 0.5, but the declared workload must stay well under it
        pad_frac_ceiling=0.30,
        # static model: 0.052 at batch cap 1 (worst — the per-request
        # fallback shape; batching raises intensity monotonically, the
        # quantitative case for the micro-batcher)
        intensity_floor=0.04,
    ),
    EntryPoint(
        name="tfidf_score_impacted_batch",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_score_impacted_batch,
        # the bucket planner + carried-cap policy live in serving/server.py
        # over grow_chunk_cap; the CSC offsets come from serving/artifact.py
        # (and segment sets re-derive them in serving/segments.py) — a
        # change to any of them must re-verify this contract
        watch=(
            f"{_PKG}/serving/server.py",
            f"{_PKG}/serving/artifact.py",
            f"{_PKG}/serving/segments.py",
            f"{_PKG}/models/tfidf.py",
            f"{_PKG}/dataflow/ingest.py",
        ),
        # one compile per (padded batch cap, carried bucket cap) point of
        # the declared matrices — anything beyond means an unpadded shape
        # reached jit on the latency path
        max_compiles=8,
        pad_plan=_impacted_pad_plan,
        # the declared raw bucket counts fill ~44% of the carried pow2
        # caps (pad_frac ~0.56 includes the 2**IMPACT_MIN_BUCKET_BITS
        # floor at tiny batches); bounded so planner drift cannot silently
        # triple the dispatched bucket axis
        pad_frac_ceiling=0.62,
        # donation contract: the scorer must alias NOTHING — every operand
        # (postings, weight table, prior) is reused by the next batch, so
        # a donation sneaking in would consume live serving state
        donate=(),
        intensity_floor=0.03,  # static model: 0.049 at b1-c64 (worst —
        # the single-request floor shape; larger batches amortize the
        # postings traffic exactly like the COO entry's matrix does)
    ),
    EntryPoint(
        name="tfidf_topk_merge",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_topk_merge,
        watch=(f"{_PKG}/serving/server.py",),
        # one compile per live-segment count at the warmed batch cap
        max_compiles=2,
        # same must-alias-nothing contract as the scorer: per-segment
        # candidate buffers belong to their dispatches
        donate=(),
        intensity_floor=0.03,  # static model measures 0.053 (s2)
    ),
)
