"""Declarative registry of the package's jit entry points for tier-2
(semantic) analysis.

Each :class:`EntryPoint` names one jit-compiled program that production
code dispatches — the PageRank iteration loops (single-chip and sharded),
the TF-IDF batch pipeline, the streaming/sharded chunk-ingest kernels, the
finalize pass and query scoring — together with how to *trace* it on the
CPU backend from abstract ``ShapeDtypeStruct`` inputs: no FLOPs run, only
trace-time Python.  The semantic analyzer (``analysis/semantic.py``)
traces every registered entry under its declared shape matrix and checks
the invariants no lexical rule can see: compile count across the matrix,
64-bit dtype leaks under x64, host callbacks per traced step, and
collective axis names / communication volume against the declared mesh
contract.

Declaring a new jit entry point (see README "Static analysis"):

1. write a ``_build_<name>()`` returning a :class:`Traceable` — the
   function to trace, one ``(label, args)`` variant per point of the shape
   matrix production feeds it (apply the caller's real padding/bucketing
   policy when building the matrix, e.g. ``grow_chunk_cap``), and an
   ``anchor`` (the public function findings should point at);
2. append an :class:`EntryPoint` to ``ENTRY_POINTS`` with the budgets the
   program is designed to meet — ``max_compiles`` (distinct trace
   signatures the matrix may produce), ``transfer_budget`` (host-callback
   eqns per step, almost always 0), and for shard_map'd programs the
   declared ``axes`` plus a ``collective_budget``;
3. ``python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis
   --tier 2`` must stay clean.

jax and the package modules are imported lazily inside the builders so
tier-1 linting never pays (or depends on) a jax import.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

# Shape-matrix sizes for the streaming ingest entries: raw per-chunk token
# counts as production sees them (mixed Wikipedia-scale chunks plus one
# exactly-at-capacity chunk).  The registry feeds them through the REAL
# caller-side padding policy (models.tfidf.grow_chunk_cap); if that policy
# ever stops bucketing, the distinct-signature count jumps past
# ``max_compiles`` and the recompile-per-shape gate fires.
CHUNK_TOKEN_MATRIX = (9_000, 120_000, 97_531, 131_072)


@dataclasses.dataclass(frozen=True)
class Traceable:
    """What the analyzer actually traces for one entry point."""

    fn: Callable  # callable accepting one variant's args
    variants: Sequence[tuple[str, tuple]]  # (label, args) per matrix point
    anchor: Callable | None = None  # public fn findings point at (else fn)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One registered jit entry point plus the budgets it must meet."""

    name: str
    module: str  # repo-relative path of the module under contract
    build: Callable[[], Traceable]
    # Other repo-relative modules the contract depends on (the shape policy
    # a shape matrix runs through, the mesh axis constants...): a
    # --changed-only run re-traces this entry when any of them changed,
    # not just ``module``.
    watch: tuple[str, ...] = ()
    max_compiles: int = 1  # distinct trace signatures the matrix may yield
    transfer_budget: int = 0  # host-callback eqns allowed per traced step
    axes: tuple[str, ...] = ()  # declared mesh axes (shard_map entries)
    collective_budget: int | None = None  # comm eqns per step (None = ungated)
    allow_64bit: bool = False  # opt out of the implicit-promotion gate
    suppress: frozenset = frozenset()  # semantic rule ids to skip


_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _f32(shape):
    import numpy as np

    return _sds(shape, np.float32)


def _i32(shape):
    import numpy as np

    return _sds(shape, np.int32)


def _device_graph_spec(n: int, e: int):
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.ops.pagerank import DeviceGraph

    return DeviceGraph(
        src=_i32((e,)),
        dst=_i32((e,)),
        inv_outdeg=_f32((n,)),
        dangling=_f32((n,)),
        has_outlinks=_f32((n,)),
        indptr=_sds((n + 1,), np.int32),
    )


# ----------------------------------------------------------------- pagerank


def _build_pagerank_scan() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e = 64, 256
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    run = ops.make_pagerank_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.pagerank_step,
    )


def _build_pagerank_while_cumsum() -> Traceable:
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import pagerank as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    n, e = 64, 256
    cfg = PageRankConfig(iterations=8, tol=1e-6, spmv_impl="cumsum")
    run = ops.make_pagerank_runner(n, cfg)
    dg = _device_graph_spec(n, e)
    return Traceable(
        fn=run,
        variants=[("n64-tol", (dg, _f32((n,)), _f32((n,))))],
        anchor=ops.make_pagerank_runner,
    )


def _shrink_chain(d0: int) -> list[int]:
    """The device counts the elastic rung can rebuild onto from ``d0``:
    the power-of-two shrink chain d0, d0/2, ..., 1 (resilience/elastic.py).
    Every sharded entry traces each of them, so the semantic gates
    (promotion, transfer census, collective budget) hold for the shrunk
    meshes a degraded run executes on — not only the healthy shape."""
    chain = []
    d = d0
    while d >= 1:
        chain.append(d)
        d //= 2
    return chain


def _sharded_pagerank_traceable(strategy: str) -> Traceable:
    import jax

    from page_rank_and_tfidf_using_apache_spark_tpu.io.graph import synthetic_powerlaw
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        pagerank_sharded as ps,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        NODES_AXIS,
        make_mesh,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import PageRankConfig

    graph = synthetic_powerlaw(64, 256, seed=1)
    cfg = PageRankConfig(iterations=4, dangling="redistribute", init="uniform")
    runners: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, NODES_AXIS)
        sg = ps.partition_graph(graph, d, strategy=strategy)
        runners[d] = ps.make_sharded_runner(sg, cfg, mesh)
        args = (
            _f32((sg.n_pad,)),
            _i32(sg.src.shape),
            _i32(sg.dst.shape),
            _f32(sg.valid.shape),
            _i32(sg.local_indptr.shape),
            _f32((sg.n_pad,)),
            _f32((sg.n_pad,)),
            _f32((sg.n_pad,)),
        )
        variants.append((f"{strategy}-d{d}", args))

    def dispatch(ranks, src, dst, valid, ip, inv, dang, e):
        # per-device-count runners: the edge arrays are [d, e_dev], so the
        # leading dim names which compiled program this variant exercises
        return runners[src.shape[0]](ranks, src, dst, valid, ip, inv, dang, e)

    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ps.make_sharded_runner,
    )


def _build_pagerank_sharded_edges() -> Traceable:
    return _sharded_pagerank_traceable("edges")


def _build_pagerank_sharded_nodes_balanced() -> Traceable:
    return _sharded_pagerank_traceable("nodes_balanced")


def _build_pagerank_sharded_src() -> Traceable:
    return _sharded_pagerank_traceable("src")


# -------------------------------------------------------------------- tfidf


def _build_tfidf_batch() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import IdfMode, TfMode

    cap, n_docs, vocab = 4096, 16, 1 << 10
    fn = functools.partial(
        ops.tfidf_pipeline,
        n_docs=n_docs,
        vocab=vocab,
        tf_mode=TfMode.FREQ,
        idf_mode=IdfMode.SMOOTH,
        l2_normalize=True,
    )
    return Traceable(
        fn=fn,
        variants=[("batch4k", (_i32((cap,)), _i32((cap,)), _i32((n_docs,))))],
        anchor=ops.tfidf_pipeline,
    )


def _build_tfidf_chunk_drain() -> Traceable:
    import functools
    import logging

    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import grow_chunk_cap
    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    # Run the declared raw-token matrix through the real streaming padding
    # policy, exactly as run_tfidf_streaming would: distinct caps == distinct
    # compiles of the chunk kernel.  The recorder's cap-bump log lines are
    # production telemetry — mute them for a lint pass.
    log = logging.getLogger("pr_tfidf_tpu")
    was_disabled = log.disabled
    log.disabled = True
    try:
        metrics = MetricsRecorder()
        cap = 0
        caps: list[int] = []
        for raw in CHUNK_TOKEN_MATRIX:
            cap, _ = grow_chunk_cap(raw, cap, metrics)
            caps.append(cap)
    finally:
        log.disabled = was_disabled
    variants = []
    for raw, cap in zip(CHUNK_TOKEN_MATRIX, caps):
        variants.append(
            (
                f"tokens{raw}",
                (_i32((cap,)), _i32((cap,)), _sds((cap,), np.bool_)),
            )
        )
    fn = functools.partial(ops.chunk_counts, vocab=1 << 10)
    return Traceable(fn=fn, variants=variants, anchor=ops.chunk_counts)


def _build_tfidf_sharded_ingest() -> Traceable:
    import jax
    import numpy as np

    from page_rank_and_tfidf_using_apache_spark_tpu.parallel import (
        tfidf_sharded as ts,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.parallel.mesh import (
        DATA_AXIS,
        make_mesh,
    )

    cap, vocab = 2048, 1 << 10
    kernels: dict[int, object] = {}
    variants: list[tuple[str, tuple]] = []
    for d in _shrink_chain(min(4, len(jax.devices()))):
        mesh = make_mesh(d, DATA_AXIS)
        kernels[d] = ts.make_sharded_counts_kernel(mesh, vocab)
        args = (
            _i32((d, cap)),
            _i32((d, cap)),
            _sds((d, cap), np.bool_),
        )
        variants.append((f"d{d}-cap{cap}", args))

    def dispatch(doc_ids, term_ids, valid):
        return kernels[doc_ids.shape[0]](doc_ids, term_ids, valid)

    return Traceable(
        fn=dispatch,
        variants=variants,
        anchor=ts.make_sharded_counts_kernel,
    )


def _build_tfidf_finalize() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import TfMode

    nnz, n_docs = 4096, 16
    fn = functools.partial(
        ops.finalize_weights, n_docs=n_docs, tf_mode=TfMode.FREQ, l2_normalize=True
    )
    return Traceable(
        fn=fn,
        variants=[
            ("nnz4k", (_i32((nnz,)), _f32((nnz,)), _i32((n_docs,)), _f32((nnz,))))
        ],
        anchor=ops.finalize_weights,
    )


def _build_tfidf_score_query() -> Traceable:
    import functools

    from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops

    cap, n_docs, vocab, k = 2048, 32, 1 << 10, 8
    result = ops.TfidfResult(
        doc=_i32((cap,)),
        term=_i32((cap,)),
        weight=_f32((cap,)),
        n_pairs=_i32(()),
        valid=_f32((cap,)),
        idf=_f32((vocab,)),
        df=_f32((vocab,)),
    )
    fn = functools.partial(ops.score_query, n_docs=n_docs, k=k)
    return Traceable(
        fn=fn,
        variants=[("top8", (result, _f32((vocab,))))],
        anchor=ops.score_query,
    )


# ------------------------------------------------------------- the registry

ENTRY_POINTS: tuple[EntryPoint, ...] = (
    EntryPoint(
        name="pagerank_step",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_scan,
    ),
    EntryPoint(
        name="pagerank_step_tol_cumsum",
        module=f"{_PKG}/ops/pagerank.py",
        build=_build_pagerank_while_cumsum,
    ),
    EntryPoint(
        name="pagerank_sharded_edges",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_edges,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # one psum per iteration: the contribs combine (replicated state
        # needs no dangling-mass or delta collective)
        collective_budget=1,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
    ),
    EntryPoint(
        name="pagerank_sharded_nodes_balanced",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_nodes_balanced,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # all_gather(weighted ranks) + psum(dangling mass) + psum(delta)
        collective_budget=3,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
    ),
    EntryPoint(
        name="pagerank_sharded_src",
        module=f"{_PKG}/parallel/pagerank_sharded.py",
        build=_build_pagerank_sharded_src,
        watch=(
            f"{_PKG}/ops/pagerank.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("nodes",),
        # reduce-scatter exchange + psum(dangling mass) + psum(delta)
        collective_budget=3,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
    ),
    EntryPoint(
        name="tfidf_batch_pipeline",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_batch,
    ),
    EntryPoint(
        name="tfidf_chunk_drain",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_chunk_drain,
        # the shape matrix runs through models/tfidf.py grow_chunk_cap —
        # a policy change there must re-verify this contract
        watch=(f"{_PKG}/models/tfidf.py",),
        # The doubling cap policy may legally produce a handful of buckets
        # over a whole stream; the declared matrix must collapse to <= 3.
        max_compiles=3,
    ),
    EntryPoint(
        name="tfidf_sharded_ingest",
        module=f"{_PKG}/parallel/tfidf_sharded.py",
        build=_build_tfidf_sharded_ingest,
        watch=(
            f"{_PKG}/ops/tfidf.py",
            f"{_PKG}/parallel/mesh.py",
            f"{_PKG}/parallel/collectives.py",
            f"{_PKG}/parallel/compat.py",
        ),
        axes=("data",),
        # exactly the DF psum — the one reduceByKey of the ingest step
        collective_budget=1,
        # one compile per device count on the elastic shrink chain (4,2,1)
        max_compiles=3,
    ),
    EntryPoint(
        name="tfidf_finalize",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_finalize,
    ),
    EntryPoint(
        name="tfidf_score_query",
        module=f"{_PKG}/ops/tfidf.py",
        build=_build_tfidf_score_query,
    ),
)
