"""Per-file AST context shared by every graftlint rule.

The rules all need the same structural facts about a module:

- which function bodies are *jit contexts* — functions decorated with
  ``@jax.jit`` / ``functools.partial(jax.jit, ...)``, functions or lambdas
  passed to ``lax.scan`` / ``lax.while_loop`` / ``lax.fori_loop`` /
  ``lax.cond`` / ``lax.map``, plus (same-file, call-by-name) functions
  reachable from those — because host syncs and data-dependent shapes are
  only hazards once XLA is tracing;
- which names inside a jit context are *traced* (a light forward taint from
  the function's non-static parameters, sanitized through ``.shape`` /
  ``.ndim`` / ``.dtype`` / ``len()`` which stay static under tracing);
- where ``# graftlint: disable=...`` suppression comments sit.

Everything is lexical + same-file by design: graftlint is a ratchet, not a
verifier, and a cheap analysis that never imports the code under scan (so
it runs even when jax is broken) beats a precise one that cannot.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

FuncNode = ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda

# Attribute accesses that turn a traced value back into a static one.
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
# Builtins whose result is static regardless of argument tracedness.
STATIC_CALLS = frozenset({"len", "isinstance", "type", "id", "repr", "str"})

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?(?:=(?P<ids>[A-Za-z0-9_,\- ]+))?"
)

# lax control-flow entry points: callee name -> positions of function args
# (every parameter of those functions is traced).
_LAX_HOF = {
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2, 3),
    "switch": None,  # positions 1.. — handled specially
    "map": (0,),
    "associative_scan": (0,),
}


def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def _is_jit_expr(node: ast.AST) -> tuple[bool, tuple[str, ...]]:
    """Does this decorator / call expression denote jax.jit?  Returns
    (is_jit, static_argnames)."""
    name = dotted_name(node)
    if name in ("jit", "jax.jit"):
        return True, ()
    if isinstance(node, ast.Call):
        cname = call_name(node)
        if cname in ("jit", "jax.jit"):
            return True, _static_argnames(node.keywords)
        # functools.partial(jax.jit, static_argnames=...)
        if cname in ("partial", "functools.partial") and node.args:
            inner = dotted_name(node.args[0])
            if inner in ("jit", "jax.jit"):
                return True, _static_argnames(node.keywords)
    return False, ()


def _static_argnames(keywords: list[ast.keyword]) -> tuple[str, ...]:
    for kw in keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return ()


def param_names(fn: FuncNode) -> list[str]:
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class FileContext:
    """All the per-file facts rules consume."""

    def __init__(
        self,
        relpath: str,
        source: str,
        tree: ast.Module,
        root: "Path | None" = None,
    ):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # Repository root of the scanned tree, for the few rules that need
        # one cross-file fact (e.g. env-knob-drift reads the declared knob
        # set out of utils/config.py).  None for bare snippet lints.
        self.root = root

        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        # suppressions
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        for lineno, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            ids = (
                {s.strip() for s in m.group("ids").split(",") if s.strip()}
                if m.group("ids")
                else {"all"}
            )
            if m.group("file"):
                self.file_suppressions |= ids
            else:
                self.line_suppressions.setdefault(lineno, set()).update(ids)

        # jit contexts
        self.jit_roots: dict[FuncNode, tuple[str, ...]] = {}  # fn -> static names
        self.lax_bodies: set[FuncNode] = set()
        self._find_jit_roots()
        self._find_lax_bodies()
        self.jit_context_funcs: set[FuncNode] = set(self.jit_roots) | set(
            self.lax_bodies
        )
        self._propagate_reachability()

        # names bound to jit-wrapped callables at module/function level,
        # e.g. ``run = jax.jit(loop)`` or a def decorated with @jit.
        self.jit_value_names: set[str] = {
            fn.name for fn in self.jit_roots if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                is_jit, _ = _is_jit_expr(node.value)
                cname = call_name(node.value)
                if is_jit or cname in ("jit", "jax.jit"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.jit_value_names.add(tgt.id)

    # ------------------------------------------------------------------ build

    def _functions(self) -> Iterator[FuncNode]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                yield node

    def _find_jit_roots(self) -> None:
        for fn in self._functions():
            if isinstance(fn, ast.Lambda):
                continue
            for dec in fn.decorator_list:
                is_jit, static = _is_jit_expr(dec)
                if is_jit:
                    self.jit_roots[fn] = static
                    break

    def _find_lax_bodies(self) -> None:
        # defs by name, for resolving ``lax.while_loop(cond, body, ...)``
        defs_by_name: dict[str, list[FuncNode]] = {}
        for fn in self._functions():
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(fn.name, []).append(fn)

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            if cname is None:
                continue
            leaf = cname.rsplit(".", 1)[-1]
            # bare ``map``/``cond`` shadow common host-side names; require a
            # lax/jax prefix for those, allow bare spellings only for the
            # unambiguous loop combinators (``from jax.lax import scan``).
            bare_ok = leaf in ("scan", "while_loop", "fori_loop", "associative_scan")
            root_ok = (cname == leaf and bare_ok) or cname.startswith(
                ("lax.", "jax.lax.")
            )
            if leaf not in _LAX_HOF or not root_ok:
                continue
            positions = _LAX_HOF[leaf]
            if positions is None:  # switch(index, [branches...]) or *branches
                args = node.args[1:]
            else:
                args = [node.args[i] for i in positions if i < len(node.args)]
            for arg in args:
                if isinstance(arg, ast.Lambda):
                    self.lax_bodies.add(arg)
                elif isinstance(arg, ast.Name):
                    for fn in defs_by_name.get(arg.id, []):
                        self.lax_bodies.add(fn)
                elif isinstance(arg, (ast.List, ast.Tuple)):
                    for e in arg.elts:
                        if isinstance(e, ast.Lambda):
                            self.lax_bodies.add(e)
                        elif isinstance(e, ast.Name):
                            for fn in defs_by_name.get(e.id, []):
                                self.lax_bodies.add(fn)

    def _propagate_reachability(self) -> None:
        """Same-file call-by-name reachability: a def called from a jit
        context is itself a jit context (its body runs under tracing)."""
        defs_by_name: dict[str, list[FuncNode]] = {}
        for fn in self._functions():
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(fn.name, []).append(fn)

        changed = True
        while changed:
            changed = False
            for ctx_fn in list(self.jit_context_funcs):
                for node in ast.walk(ctx_fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = call_name(node)
                    if cname is None or "." in cname:
                        continue  # same-file plain names only
                    for fn in defs_by_name.get(cname, []):
                        if fn not in self.jit_context_funcs:
                            self.jit_context_funcs.add(fn)
                            changed = True

    # ------------------------------------------------------------------ query

    def enclosing_function(self, node: ast.AST) -> FuncNode | None:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def in_jit_context(self, node: ast.AST) -> bool:
        fn = self.enclosing_function(node)
        while fn is not None:
            if fn in self.jit_context_funcs:
                return True
            fn = self.enclosing_function(fn)
        return False

    def enclosing_loops(self, node: ast.AST) -> list[ast.For | ast.While]:
        """Python for/while statements lexically containing ``node``."""
        out: list[ast.For | ast.While] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.append(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break  # don't escape into the enclosing function's loops
            cur = self.parents.get(cur)
        return out

    def is_suppressed(self, rule_id: str, lineno: int) -> bool:
        if {"all", rule_id} & self.file_suppressions:
            return True
        ids = self.line_suppressions.get(lineno, set())
        return bool({"all", rule_id} & ids)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # ------------------------------------------------------------- taint pass

    def traced_names(self, fn: FuncNode) -> set[str]:
        """Names holding traced values inside ``fn``.

        Seeds: the function's parameters minus jit static_argnames (for
        @jit roots) — or all parameters for lax loop/branch bodies.  For
        plain defs merely *reachable* from a jit context the seed is empty:
        whether their params are traced depends on call sites, and guessing
        produces false tracer-branch positives (e.g. static ``impl=`` mode
        strings threaded through helpers).  Propagates through assignments;
        ``.shape``/``.ndim``/``.dtype``/``.size``/``len()`` sanitize.
        """
        traced: set[str] = set()
        if fn in self.jit_roots:
            static = set(self.jit_roots[fn])
            traced |= {p for p in param_names(fn) if p not in static}
        elif fn in self.lax_bodies:
            traced |= set(param_names(fn))

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for _ in range(2):  # two passes reach a fixpoint for straight-line use
            for stmt in body:
                for node in _walk_skipping_nested_functions(stmt):
                    targets: list[ast.expr] = []
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AugAssign):
                        targets, value = [node.target], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        targets, value = [node.target], node.value
                    else:
                        continue
                    if self.expr_is_traced(value, traced):
                        for tgt in targets:
                            for name in _target_names(tgt):
                                traced.add(name)
        return traced

    def expr_is_traced(self, expr: ast.AST, traced: set[str]) -> bool:
        """Does ``expr`` (evaluated inside a jit context) yield a traced
        value, given the currently-known traced names?"""
        if isinstance(expr, ast.Name):
            return expr.id in traced
        if isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in STATIC_ATTRS:
                return False  # x.shape et al. are static under tracing
            return self.expr_is_traced(expr.value, traced)
        if isinstance(expr, ast.Call):
            cname = call_name(expr)
            if cname in STATIC_CALLS:
                return False
            args = list(expr.args) + [kw.value for kw in expr.keywords]
            if any(self.expr_is_traced(a, traced) for a in args):
                return True
            # method call on a traced value: x.astype(...), x.sum(), ...
            if isinstance(expr.func, ast.Attribute):
                return self.expr_is_traced(expr.func.value, traced)
            return False
        if isinstance(expr, ast.Subscript):
            return self.expr_is_traced(expr.value, traced) or self.expr_is_traced(
                expr.slice, traced
            )
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_is_traced(e, traced) for e in expr.elts)
        if isinstance(expr, ast.Slice):
            return any(
                self.expr_is_traced(e, traced)
                for e in (expr.lower, expr.upper, expr.step)
                if e is not None
            )
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_is_traced(v, traced) for v in expr.values)
        if isinstance(expr, ast.BinOp):
            return self.expr_is_traced(expr.left, traced) or self.expr_is_traced(
                expr.right, traced
            )
        if isinstance(expr, ast.UnaryOp):
            return self.expr_is_traced(expr.operand, traced)
        if isinstance(expr, ast.Compare):
            return self.expr_is_traced(expr.left, traced) or any(
                self.expr_is_traced(c, traced) for c in expr.comparators
            )
        if isinstance(expr, ast.IfExp):
            return any(
                self.expr_is_traced(e, traced)
                for e in (expr.test, expr.body, expr.orelse)
            )
        return False


def _target_names(tgt: ast.expr) -> Iterator[str]:
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            yield from _target_names(e)
    elif isinstance(tgt, ast.Starred):
        yield from _target_names(tgt.value)


def _walk_skipping_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk, but do not descend into nested function definitions (they
    get their own taint pass)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield from _walk_skipping_nested_functions(child)
