"""graftlint engine: file discovery, rule dispatch, ratchet baseline.

The ratchet contract (ISSUE 1): ``analysis/baseline.json`` freezes the
findings that existed when a rule landed, each with a one-line
justification.  A lint run fails (exit 1) only on findings *not* in the
baseline, so the count can only ratchet down: fixing code lets baseline
entries be deleted; new violations can never ship silently.  Stale
baseline entries (fixed code) are reported so they get pruned.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Iterable, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import FileContext
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import RULES

# Directories never worth scanning.
_SKIP_DIRS = {"__pycache__", ".git", "build", ".pytest_cache", "node_modules"}


def repo_root() -> Path:
    """The repository root: parent of the installed package directory."""
    return Path(__file__).resolve().parents[2]


def default_targets(root: Path | None = None) -> list[Path]:
    """The tier-1 scan surface: the package, tools/, and bench.py."""
    root = root or repo_root()
    targets = [root / "page_rank_and_tfidf_using_apache_spark_tpu"]
    for extra in (root / "tools", root / "bench.py"):
        if extra.exists():
            targets.append(extra)
    return targets


def iter_python_files(paths: Sequence[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    yield f


def changed_python_files(root: Path, base: str = "HEAD") -> list[Path]:
    """Python files changed vs ``base`` (worktree diff + untracked), for
    ``--changed-only``.  Raises ``RuntimeError`` when git cannot answer —
    the caller should fall back to a full scan, never silently lint
    nothing."""
    import subprocess

    names: set[str] = set()
    for cmd in (
        ["git", "-C", str(root), "diff", "--name-only", base, "--"],
        ["git", "-C", str(root), "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git failed ({' '.join(cmd)}): {proc.stderr.strip()}"
            )
        names.update(n for n in proc.stdout.splitlines() if n.endswith(".py"))
    return sorted(root / n for n in names if (root / n).exists())


def lint_file(path: Path, root: Path) -> list[Finding]:
    source = path.read_text(encoding="utf-8")
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [
            Finding(
                rule="parse-error",
                path=rel,
                line=exc.lineno or 1,
                col=(exc.offset or 1) - 1,
                message=f"file does not parse: {exc.msg}",
                snippet="",
            )
        ]

    ctx = FileContext(rel, source, tree, root=root)
    findings: list[Finding] = []
    for rule in RULES.values():
        for node, message in rule.check(ctx):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            if ctx.is_suppressed(rule.id, line):
                continue
            findings.append(
                Finding(
                    rule=rule.id,
                    path=rel,
                    line=line,
                    col=col,
                    message=message,
                    snippet=ctx.snippet(line),
                )
            )
    return findings


def run_lint(paths: Sequence[Path], root: Path | None = None) -> list[Finding]:
    root = root or repo_root()
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(lint_file(f, root))
    return assign_fingerprints(findings)


# ----------------------------------------------------------------- baseline


@dataclasses.dataclass
class RatchetResult:
    new: list[Finding]
    known: list[Finding]
    stale: list[dict]  # baseline entries whose finding no longer exists

    @property
    def ok(self) -> bool:
        return not self.new


def baseline_path(root: Path | None = None) -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Path) -> dict[str, dict]:
    """fingerprint -> entry.  Missing file means an empty baseline."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    entries = data.get("entries", [])
    return {e["fingerprint"]: e for e in entries}


def apply_ratchet(findings: list[Finding], baseline: dict[str, dict]) -> RatchetResult:
    new: list[Finding] = []
    known: list[Finding] = []
    seen: set[str] = set()
    for f in findings:
        if f.fingerprint in baseline:
            known.append(f)
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = [e for fp, e in baseline.items() if fp not in seen]
    return RatchetResult(new=new, known=known, stale=stale)


def write_baseline(
    path: Path,
    findings: list[Finding],
    justifications: dict[str, str] | None = None,
    scanned_paths: set[str] | None = None,
) -> None:
    """Write/refresh the ratchet file.  Re-uses justifications from an
    existing baseline for unchanged fingerprints; new entries get a
    placeholder that code review is expected to replace.

    ``scanned_paths`` (repo-relative) limits the refresh to files this run
    actually analyzed: existing entries for *other* files are carried over
    untouched, so a partial ``--write-baseline some_file.py`` cannot wipe
    the rest of the ratchet."""
    old = load_baseline(path)
    justifications = justifications or {}
    entries = []
    if scanned_paths is not None:
        entries.extend(
            e for e in old.values() if e.get("path") not in scanned_paths
        )
    for f in findings:
        just = (
            justifications.get(f.fingerprint)
            or old.get(f.fingerprint, {}).get("justification")
            or "UNREVIEWED — replace with a one-line justification"
        )
        entries.append(
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "snippet": f.snippet,
                "justification": just,
            }
        )
    doc = {
        "version": 1,
        "comment": (
            "graftlint ratchet baseline: pre-existing findings frozen with "
            "justifications. New findings FAIL lint. Fix code -> delete the "
            "entry. Never add entries for ops/ or parallel/ without a "
            "reviewed justification."
        ),
        "entries": entries,
    }
    path.write_text(json.dumps(doc, indent=2) + "\n")
