"""Finding data model for graftlint.

A ``Finding`` is one rule violation at one source location.  Findings are
identified across commits by a *fingerprint* — a hash of (rule, path,
normalized source line, occurrence index) that is stable under pure
line-number shifts — which is what the ratchet baseline
(``analysis/baseline.json``) stores.  Everything here is stdlib-only: the
analyzer must run (and fail CI) even when jax itself is broken or absent.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Iterable


@dataclasses.dataclass
class Finding:
    """One rule violation."""

    rule: str  # rule id, e.g. "host-sync-in-loop"
    path: str  # repo-root-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str  # stripped source line text
    fingerprint: str = ""  # assigned by assign_fingerprints()

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: [{self.rule}] {self.message}"


def _normalize(snippet: str) -> str:
    """Whitespace-insensitive form of the flagged line, so re-indenting a
    block does not invalidate baseline entries."""
    return " ".join(snippet.split())


def assign_fingerprints(findings: Iterable[Finding]) -> list[Finding]:
    """Assign stable fingerprints, disambiguating identical (rule, path,
    line-text) triples by occurrence order top-to-bottom."""
    out = sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
    seen: dict[tuple[str, str, str], int] = {}
    for f in out:
        key = (f.rule, f.path, _normalize(f.snippet))
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        raw = "\0".join([key[0], key[1], key[2], str(idx)])
        f.fingerprint = hashlib.sha256(raw.encode()).hexdigest()[:16]
    return out


def render_human(findings: list[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def render_json(findings: list[Finding], **extra: Any) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in findings], **extra}, indent=2
    )
