"""graftlint tier 6: distributed wire-protocol analysis (ISSUE 18).

Spark's RPC layer is a *checked* contract: every message class is a
serializable case class both endpoints compile against, and
``TransportConf`` pins the retry/timeout policy next to the transport —
an executor cannot invent a status its driver does not classify.  The
serving fabric (ISSUE 17) re-created that surface as an informal
convention spread across ``serving/fabric.py``: endpoints, status codes,
request-id replay, and the generation floor are conventions the router
and replica merely *agree* on.  A drifted status code is a
dropped-request class — the router's retry loop can only classify what
it knows about — and a side effect ahead of the request-id dedup guard
silently breaks the dropped=0 / double_served=0 audit the fleet is built
on.

Tier 6 is the static gate for that defect class.  Like tiers 1, 4 and 5
it is stdlib-only — pure AST over the wire surface (``serving/fabric.py``,
``obs/export.py``, ``cli/serve.py``), no jax import, whole-repo well
under the declared ``GRAFT_PROTO_BUDGET_S`` budget — driven by the
``analysis/registry.py WIRE_SCHEMAS`` contract and validated BOTH
directions, the ``DONATED_CALLEES``/``ARTIFACT_SCHEMAS`` style:

- **endpoint-contract-drift** — a handler returns a status code or
  writes a response key the contract does not declare; the router reads
  an undeclared response key or posts an undeclared request key; a
  declared code/key no code emits/reads; a ``routes=`` registration
  missing from the contract or a contract row naming no real route.
- **status-class-drift** — every declared status code must carry a
  router-side class (``success``/``terminal``/``retryable``/``suspect``)
  consistent with the router's lexical retry logic: a code the router
  raises on must be declared terminal, a retryable code must not be
  raised on, and 503 (replica below the generation floor / shutting
  down) MUST be retryable — the poll loop catches the replica up, a
  terminal 503 would drop the request.
- **retry-unsafe-effect** — any side effect lexically reachable from a
  replayed route's handler (counter mutation, latency append, cache
  write, a seal/commit call; same-file call propagation as in tier 4)
  must sit *behind* the request-id dedup guard — an effect ahead of the
  guard executes twice when the router re-dispatches a rid.
- **floor-monotonicity** — the floor writer (``commit_floor``) must
  stage + ``durable_replace`` (never a raw rename), and every store to a
  ``.floor`` attribute outside ``__init__`` must be guarded by an upward
  comparison (or a ``max(...)``): the generation floor only ratchets up.

The model also *derives* the tier's dynamic proof:
:func:`enumerate_message_space` walks the contract plus the handler's
lexical request parse (subscript = required key, ``.get`` = optional)
and lists every malformed / out-of-contract / duplicate-rid /
stale-floor probe ``tools/protocol_harness.py`` replays at a live
replica, asserting typed rejection — never a hang, never a second
execution.  :func:`wire_fingerprint` hashes the parsed contract so
bench rounds can stamp which protocol generation their fabric numbers
were measured against (``tools/trace_diff.py`` arms fresh across a
fingerprint change instead of comparing).

Findings flow through the same suppression (``# graftlint:
disable=<rule>``) and fingerprint/baseline/ratchet machinery as every
other tier.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
from pathlib import Path
from typing import Iterator

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.concurrency import (
    _Sink,
    _walk_own,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import (
    FileContext,
    FuncNode,
    call_name,
    dotted_name,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.persistence import (
    _collect_read,
    _collect_written,
    _literal_strings,
    _resolve_str,
    _split_spec,
)

PROTO_RULES: dict[str, str] = {
    "endpoint-contract-drift": (
        "handler/router wire surface drifted from the declared "
        "WIRE_SCHEMAS contract: an undeclared status code or "
        "request/response key is emitted or read, a declared one is "
        "dead, or a registered route and the contract disagree"
    ),
    "status-class-drift": (
        "a declared status code's retry class contradicts the router's "
        "lexical retry logic (or is missing/unknown) — an unclassified "
        "or misclassified code is a dropped-request class; 503-below-"
        "floor must be retryable"
    ),
    "retry-unsafe-effect": (
        "a side effect reachable from a replayed route sits ahead of "
        "the request-id dedup guard — a router re-dispatch would "
        "execute it twice (double-serve / double-count)"
    ),
    "floor-monotonicity": (
        "the generation-floor writer bypasses durable_replace, or a "
        ".floor store is not guarded by an upward comparison — the "
        "floor only ratchets up, and never through a torn write"
    ),
}

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"

# The wire surface this tier always parses, contract rows aside.
SCAN_MODULES: tuple[str, ...] = (
    f"{_PKG}/serving/fabric.py",
    f"{_PKG}/obs/export.py",
    f"{_PKG}/cli/serve.py",
)

_STATUS_CLASSES = frozenset({"success", "terminal", "retryable", "suspect"})

# The request-id dedup guard attribute(s): a replayed route's handler must
# consult one of these before any side effect executes.
_DEDUP_GUARDS = frozenset({"_rid_cache"})

# Floor-protocol leaves (shared convention with tier 5's fabric_floor
# ARTIFACT_SCHEMAS row and the crash harness's 'floor' scenario).
_FLOOR_WRITERS = frozenset({"commit_floor"})
_DURABLE_LEAVES = frozenset({"durable_replace"})

# Mutating-call leaves that count as side effects inside a replay handler
# (receiver-attribute mutations), and commit-protocol leaves that always do.
_MUTATOR_LEAVES = frozenset({"append", "appendleft", "add", "update",
                             "extend", "insert", "setdefault"})
_COMMIT_LEAVES = frozenset({"commit_append", "commit_replace",
                            "commit_floor", "seal_segment",
                            "merge_segments"})


# --------------------------------------------------------------------------
# the declared wire contract (parsed lexically from the registry)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WireRow:
    endpoint: str
    method: str
    path: str
    handler: str
    readers: tuple
    request_keys: tuple
    response_keys: tuple
    aux_keys: tuple
    status_classes: tuple  # ((code:int, class:str), ...)


@dataclasses.dataclass(frozen=True)
class WireContract:
    rows: tuple  # WireRow rows
    relpath: str | None  # registry path when under the scanned root
    line: int


def _literal_status_pairs(node: ast.AST, consts: dict[str, str]) -> tuple:
    """``((200, "success"), ...)`` rows: int-literal code + class string."""
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if not isinstance(e, (ast.Tuple, ast.List)) or len(e.elts) != 2:
                continue
            code_node, cls_node = e.elts
            cls = _resolve_str(cls_node, consts)
            if isinstance(code_node, ast.Constant) and \
                    isinstance(code_node.value, int) and cls is not None:
                out.append((code_node.value, cls))
    return tuple(out)


def _parse_contract_file(path: Path) -> tuple | None:
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    consts: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Constant) and \
                isinstance(stmt.value.value, str):
            consts[stmt.targets[0].id] = stmt.value.value
    for node in ast.walk(tree):
        value: ast.expr | None = None
        name: str | None = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if name != "WIRE_SCHEMAS" or \
                not isinstance(value, (ast.Tuple, ast.List)):
            continue
        rows = []
        for row in value.elts:
            if not isinstance(row, (ast.Tuple, ast.List)) or \
                    len(row.elts) != 9:
                continue
            endpoint = _resolve_str(row.elts[0], consts)
            method = _resolve_str(row.elts[1], consts)
            rpath = _resolve_str(row.elts[2], consts)
            handler = _resolve_str(row.elts[3], consts)
            if None in (endpoint, method, rpath, handler):
                continue
            rows.append(WireRow(
                endpoint=endpoint,
                method=method,
                path=rpath,
                handler=handler,
                readers=_literal_strings(row.elts[4], consts),
                request_keys=_literal_strings(row.elts[5], consts),
                response_keys=_literal_strings(row.elts[6], consts),
                aux_keys=_literal_strings(row.elts[7], consts),
                status_classes=_literal_status_pairs(row.elts[8], consts),
            ))
        return tuple(rows), node.lineno
    return None


_contract_cache: dict[str, WireContract | None] = {}


def wire_contract(root: Path) -> WireContract | None:
    key = str(root)
    if key in _contract_cache:
        return _contract_cache[key]
    candidates = [
        (root / f"{_PKG}/analysis/registry.py", True),
        (root / "analysis/registry.py", True),
        (Path(__file__).resolve().parent / "registry.py", False),
    ]
    contract = None
    for path, in_root in candidates:
        if path.exists():
            parsed = _parse_contract_file(path)
            if parsed is None:
                continue
            rows, line = parsed
            relpath = None
            if in_root:
                try:
                    relpath = path.resolve().relative_to(
                        root.resolve()).as_posix()
                except ValueError:
                    relpath = path.as_posix()
            contract = WireContract(rows=rows, relpath=relpath, line=line)
            break
    _contract_cache[key] = contract
    return contract


def wire_fingerprint(root: Path | None = None) -> str | None:
    """A stable hash of the *parsed* wire contract — the protocol
    generation a bench round's fabric numbers were measured against.
    Formatting-independent: two registries declaring the same rows hash
    identically."""
    root = root or repo_root()
    contract = wire_contract(root)
    if contract is None:
        return None
    doc = json.dumps([dataclasses.astuple(r) for r in contract.rows],
                     sort_keys=True)
    return hashlib.sha256(doc.encode("utf-8")).hexdigest()[:16]


# --------------------------------------------------------------------------
# per-file model
# --------------------------------------------------------------------------


class _WFile:
    """Per-file wire-surface facts (duck-compatible with the tier-5
    collectors: exposes ``iter_scope``/``resolve_def``/``ctx``)."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.defs: dict[str, list[FuncNode]] = {}
        self.def_class: dict[int, str | None] = {}
        self.funcs: list[FuncNode] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
                self.funcs.append(node)
                cls = None
                cur = ctx.parents.get(node)
                while cur is not None:
                    if isinstance(cur, ast.ClassDef):
                        cls = cur.name
                        break
                    cur = ctx.parents.get(cur)
                self.def_class[id(node)] = cls

    def resolve_def(self, funcpart: str) -> FuncNode | None:
        cls = None
        name = funcpart
        if "." in funcpart:
            cls, name = funcpart.split(".", 1)
        for fn in self.defs.get(name, []):
            if cls is None or self.def_class.get(id(fn)) == cls:
                return fn
        return None

    def body_of(self, fn: FuncNode | None) -> list[ast.AST]:
        if fn is None:
            return list(self.ctx.tree.body)
        return fn.body if isinstance(fn.body, list) else [fn.body]

    def iter_scope(self, fn: FuncNode | None) -> Iterator[ast.AST]:
        """Nodes lexically in ``fn``'s own scope — without descending
        into nested defs, but *including* lambdas: the router posts its
        request doc through ``attempt_once(lambda: self._post_json(...))``
        and that body executes inline per request, so its keys and
        effects belong to the enclosing function."""

        def walk(node: ast.AST) -> Iterator[ast.AST]:
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                yield from walk(child)

        for stmt in self.body_of(fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from walk(stmt)

    def same_file_callees(self, fn: FuncNode) -> list[tuple[ast.Call, FuncNode]]:
        """(call site, callee def) pairs for bare-name and self-method
        calls resolving inside this file — tier 4's propagation idiom."""
        out: list[tuple[ast.Call, FuncNode]] = []
        for node in self.iter_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            leaf = cname.rsplit(".", 1)[-1] if cname else None
            if leaf is None:
                continue
            if cname != leaf and not (cname == f"self.{leaf}"):
                continue
            for callee in self.defs.get(leaf, []):
                out.append((node, callee))
                break
        return out


def build_models(root: Path,
                 extra: "tuple[str, ...] | None" = None) -> dict[str, _WFile]:
    """Parse the wire surface (SCAN_MODULES + contract-named modules +
    the registry itself) into per-file models.  Tier 6 deliberately does
    NOT model the whole repo: the wire protocol lives on a declared
    surface, and a bounded parse keeps the gate far under its budget."""
    contract = wire_contract(root)
    rels: set[str] = set(SCAN_MODULES)
    if contract is not None:
        if contract.relpath:
            rels.add(contract.relpath)
        for row in contract.rows:
            rels.add(_split_spec(row.handler)[0])
            for spec in row.readers:
                rels.add(_split_spec(spec)[0])
    rels.update(extra or ())
    models: dict[str, _WFile] = {}
    for rel in sorted(rels):
        f = root / rel
        try:
            source = f.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(f))
        except (OSError, SyntaxError):
            continue  # tier 1 reports parse errors
        models[rel] = _WFile(FileContext(rel, source, tree, root=root))
    return models


# --------------------------------------------------------------------------
# lexical extraction helpers
# --------------------------------------------------------------------------


def _emitted_codes(model: _WFile, fn: FuncNode) -> dict[int, ast.AST]:
    """Status codes ``fn`` can emit: the int-literal first element of a
    ``(code, ctype, body)`` response tuple (returned directly or staged
    through a local like handle_query's cached ``resp``), or the
    int-literal first argument of a ``_send(code, ...)`` dispatch."""
    out: dict[int, ast.AST] = {}
    for node in model.iter_scope(fn):
        if isinstance(node, ast.Tuple) and \
                isinstance(getattr(node, "ctx", None), ast.Load) and \
                2 <= len(node.elts) <= 3:
            first = node.elts[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, int) and \
                    100 <= first.value <= 599:
                out.setdefault(first.value, node)
        elif isinstance(node, ast.Call):
            cname = call_name(node) or ""
            if cname.rsplit(".", 1)[-1] == "_send" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, int):
                    out.setdefault(first.value, node)
    return out


def _scope_str_consts(model: _WFile, fn: FuncNode) -> set[str]:
    return {
        n.value for n in model.iter_scope(fn)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def _required_request_keys(model: _WFile, fn: FuncNode,
                           recv: str) -> tuple[set[str], set[str]]:
    """(required, optional) request keys as the handler lexically parses
    them: a ``recv["k"]`` subscript raises KeyError when absent
    (required); a ``recv.get("k", ...)`` carries a default (optional)."""
    required: set[str] = set()
    optional: set[str] = set()
    for node in model.iter_scope(fn):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str) and \
                dotted_name(node.value) == recv:
            required.add(node.slice.value)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str) and \
                dotted_name(node.func.value) == recv:
            optional.add(node.args[0].value)
    return required, optional


def _registered_routes(models: dict[str, _WFile]) -> dict[tuple, tuple]:
    """``(method, path) -> (model relpath, node)`` for every route
    registered through a ``routes={(method, path): handler}`` literal."""
    out: dict[tuple, tuple] = {}
    for rel in sorted(models):
        model = models[rel]
        for node in ast.walk(model.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg != "routes" or not isinstance(kw.value, ast.Dict):
                    continue
                for k in kw.value.keys:
                    if isinstance(k, ast.Tuple) and len(k.elts) == 2 and \
                            all(isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                                for e in k.elts):
                        out.setdefault(
                            (k.elts[0].value, k.elts[1].value),
                            (rel, k),
                        )
    return out


def _resolve_spec(models: dict[str, _WFile],
                  spec: str) -> "tuple[_WFile, FuncNode, str | None] | None":
    path, funcpart, recv = _split_spec(spec)
    model = models.get(path)
    fn = model.resolve_def(funcpart) if model is not None else None
    if model is None or fn is None:
        return None
    return model, fn, recv


# --------------------------------------------------------------------------
# check A+B: endpoint-contract-drift / status-class-drift
# --------------------------------------------------------------------------


def _find_router(models: dict[str, _WFile],
                 contract: WireContract) -> "tuple[_WFile, FuncNode] | None":
    """The router function: the first declared reader containing an
    ``except HTTPError`` handler — the retry-classification seat."""
    for row in contract.rows:
        for spec in row.readers:
            resolved = _resolve_spec(models, spec)
            if resolved is None:
                continue
            model, fn, _recv = resolved
            for node in model.iter_scope(fn):
                if isinstance(node, ast.ExceptHandler) and \
                        node.type is not None and \
                        "HTTPError" in ast.dump(node.type):
                    return model, fn
    return None


def _router_terminal_codes(model: _WFile,
                           fn: FuncNode) -> tuple[set[int], bool]:
    """(codes the router raises on, whether a retry fall-through exists)
    extracted from the ``except HTTPError`` handler's lexical shape:
    ``if exc.code == N: ... raise`` marks N terminal; a ``continue``
    anywhere else in the handler is the sibling-retry fall-through."""
    terminal: set[int] = set()
    fallthrough = False
    for node in model.iter_scope(fn):
        if not (isinstance(node, ast.ExceptHandler) and node.type is not None
                and "HTTPError" in ast.dump(node.type)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.If):
                codes = set()
                for cmp_node in ast.walk(sub.test):
                    if isinstance(cmp_node, ast.Compare) and \
                            len(cmp_node.ops) == 1 and \
                            isinstance(cmp_node.ops[0], ast.Eq):
                        for side in (cmp_node.left, *cmp_node.comparators):
                            if isinstance(side, ast.Constant) and \
                                    isinstance(side.value, int):
                                codes.add(side.value)
                if codes and any(isinstance(s, ast.Raise)
                                 for b in sub.body for s in ast.walk(b)):
                    terminal.update(codes)
            elif isinstance(sub, ast.Continue):
                fallthrough = True
    return terminal, fallthrough


def _check_contract(contract: WireContract, models: dict[str, _WFile],
                    sink: _Sink) -> None:
    reg_model = models.get(contract.relpath) if contract.relpath else None

    def reg_finding(rule: str, message: str) -> None:
        if reg_model is not None:
            sink.add(reg_model.ctx, rule, None, message, line=contract.line)

    # ---- per-handler code surfaces (a handler may serve several rows)
    handler_rows: dict[str, list[WireRow]] = {}
    for row in contract.rows:
        hpath, hfunc, _ = _split_spec(row.handler)
        handler_rows.setdefault(f"{hpath}::{hfunc}", []).append(row)

    registered = _registered_routes(models)
    declared_routes = {(r.method, r.path) for r in contract.rows}

    # routes registered in code but missing from the contract
    for (method, rpath), (rel, node) in sorted(registered.items()):
        if (method, rpath) not in declared_routes:
            sink.add(
                models[rel].ctx, "endpoint-contract-drift", node,
                f"route ({method} {rpath}) is registered on the wire "
                "surface but WIRE_SCHEMAS does not declare it — the "
                "router/harness cannot classify its codes; add a row",
            )

    router = _find_router(models, contract)
    terminal_codes: set[int] = set()
    fallthrough = False
    router_fn_id = None
    if router is not None:
        terminal_codes, fallthrough = _router_terminal_codes(*router)
        router_fn_id = id(router[1])

    for hkey, rows in sorted(handler_rows.items()):
        resolved = _resolve_spec(models, rows[0].handler)
        if resolved is None:
            reg_finding(
                "endpoint-contract-drift",
                f"WIRE_SCHEMAS handler {rows[0].handler!r} does not "
                "resolve to a function on the wire surface — stale "
                "contract row",
            )
            continue
        model, fn, recv = resolved

        # declared route must exist: registered, or a path literal the
        # handler itself dispatches on
        consts = _scope_str_consts(model, fn)
        for row in rows:
            if (row.method, row.path) not in registered and \
                    row.path not in consts:
                reg_finding(
                    "endpoint-contract-drift",
                    f"WIRE_SCHEMAS declares {row.method} {row.path} for "
                    f"endpoint {row.endpoint!r} but no routes= "
                    "registration or handler path literal serves it — "
                    "stale contract row",
                )

        # ---- status codes, both directions (unioned across the
        # handler's rows: the dispatcher serves several endpoints)
        declared_codes = {c for row in rows
                          for c, _cls in row.status_classes}
        emitted = _emitted_codes(model, fn)
        for code, node in sorted(emitted.items()):
            if code not in declared_codes:
                sink.add(
                    model.ctx, "endpoint-contract-drift", node,
                    f"handler {hkey.split('::')[-1]}() can emit HTTP "
                    f"{code} which no WIRE_SCHEMAS row declares — an "
                    "unclassified code is a dropped-request class; "
                    "declare it with its retry class",
                )
        for code in sorted(declared_codes - set(emitted)):
            reg_finding(
                "endpoint-contract-drift",
                f"WIRE_SCHEMAS declares HTTP {code} for handler "
                f"{hkey!r} which its code never emits — stale "
                "declaration",
            )

        # ---- status classes vs the router's lexical retry logic
        seen_pairs: set[tuple] = set()
        for row in rows:
            routed = any(
                (r := _resolve_spec(models, spec)) is not None
                and id(r[1]) == router_fn_id
                for spec in row.readers
            )
            for code, cls in row.status_classes:
                if (code, cls) in seen_pairs:
                    continue
                seen_pairs.add((code, cls))
                if cls not in _STATUS_CLASSES:
                    reg_finding(
                        "status-class-drift",
                        f"endpoint {row.endpoint!r}: HTTP {code} carries "
                        f"unknown class {cls!r} (expected one of "
                        f"{sorted(_STATUS_CLASSES)})",
                    )
                    continue
                if code == 503 and cls != "retryable":
                    reg_finding(
                        "status-class-drift",
                        f"endpoint {row.endpoint!r}: HTTP 503 declared "
                        f"{cls!r} — a replica below the generation floor "
                        "catches up via its poll loop; 503 must be "
                        "retryable or floor catch-up becomes a dropped "
                        "request",
                    )
                if not routed:
                    continue
                if cls == "terminal" and code not in terminal_codes:
                    reg_finding(
                        "status-class-drift",
                        f"endpoint {row.endpoint!r}: HTTP {code} is "
                        "declared terminal but the router's HTTPError "
                        "handler never raises on it — it would be "
                        "retried into the retry budget and dropped",
                    )
                if cls == "retryable" and code in terminal_codes:
                    reg_finding(
                        "status-class-drift",
                        f"endpoint {row.endpoint!r}: HTTP {code} is "
                        "declared retryable but the router raises on it "
                        "— a transient refusal becomes a caller-visible "
                        "failure",
                    )
                if cls == "retryable" and not fallthrough:
                    reg_finding(
                        "status-class-drift",
                        f"endpoint {row.endpoint!r}: HTTP {code} is "
                        "declared retryable but the router's HTTPError "
                        "handler has no retry fall-through",
                    )

        # ---- request keys: handler reads vs router writes
        for row in rows:
            if recv is not None and row.request_keys:
                required, optional = _required_request_keys(model, fn, recv)
                reads = required | optional
                keyset = set(row.request_keys)
                for k in sorted(reads - keyset):
                    sink.add(
                        model.ctx, "endpoint-contract-drift", fn,
                        f"handler reads request key {k!r} which endpoint "
                        f"{row.endpoint!r} does not declare — a router "
                        "that never sends it breaks this parse silently",
                        line=fn.lineno,
                    )
                for k in sorted(keyset - reads):
                    reg_finding(
                        "endpoint-contract-drift",
                        f"endpoint {row.endpoint!r}: declared request "
                        f"key {k!r} is read by no handler parse — stale "
                        "declaration",
                    )
            if row.method == "POST" and row.request_keys and row.readers:
                written: dict[str, tuple] = {}
                any_resolved = False
                for spec in row.readers:
                    r = _resolve_spec(models, spec)
                    if r is None:
                        reg_finding(
                            "endpoint-contract-drift",
                            f"endpoint {row.endpoint!r}: declared reader "
                            f"{spec!r} does not resolve on the wire "
                            "surface — stale contract row",
                        )
                        continue
                    any_resolved = True
                    rmodel, rfn, _rrecv = r
                    for k, node in _collect_written(rmodel, rfn).items():
                        written.setdefault(k, (rmodel, node))
                keyset = set(row.request_keys)
                for k, (rmodel, node) in sorted(written.items()):
                    if k not in keyset:
                        sink.add(
                            rmodel.ctx, "endpoint-contract-drift", node,
                            f"router posts request key {k!r} which "
                            f"endpoint {row.endpoint!r} does not declare "
                            "— the handler will silently drop it",
                        )
                if any_resolved:
                    for k in sorted(keyset - set(written)):
                        reg_finding(
                            "endpoint-contract-drift",
                            f"endpoint {row.endpoint!r}: declared "
                            f"request key {k!r} is posted by no declared "
                            "reader — stale declaration",
                        )

        # ---- response keys: handler writes vs reader reads
        for row in rows:
            keyset = set(row.response_keys)
            written = _collect_written(model, fn)
            for k, node in sorted(written.items()):
                if k not in keyset:
                    sink.add(
                        model.ctx, "endpoint-contract-drift", node,
                        f"handler writes response key {k!r} which "
                        f"endpoint {row.endpoint!r} does not declare — "
                        "add it to WIRE_SCHEMAS (and a reader, or mark "
                        "it aux) before shipping it on the wire",
                    )
            if row.response_keys:
                for k in sorted(keyset - set(written)):
                    reg_finding(
                        "endpoint-contract-drift",
                        f"endpoint {row.endpoint!r}: declared response "
                        f"key {k!r} is written by no handler — the "
                        "contract promises a member the wire never "
                        "carries",
                    )
            read: dict[str, tuple] = {}
            any_reader = False
            for spec in row.readers:
                r = _resolve_spec(models, spec)
                if r is None:
                    continue  # stale-reader finding emitted above
                any_reader = True
                rmodel, rfn, rrecv = r
                for k, node in _collect_read(rmodel, rfn, rrecv).items():
                    read.setdefault(k, (rmodel, node))
            for k, (rmodel, node) in sorted(read.items()):
                if k not in keyset:
                    sink.add(
                        rmodel.ctx, "endpoint-contract-drift", node,
                        f"reader loads response key {k!r} which endpoint "
                        f"{row.endpoint!r} does not declare — a handler-"
                        "side rename would break this load path "
                        "silently; declare the key",
                    )
            if any_reader and row.response_keys:
                aux = set(row.aux_keys)
                for k in sorted(keyset - set(read) - aux):
                    reg_finding(
                        "endpoint-contract-drift",
                        f"endpoint {row.endpoint!r}: response key {k!r} "
                        "is served but read by no declared reader — "
                        "dead wire weight, or a reader lost a member it "
                        "needs; mark it aux or wire the reader",
                    )
        for row in rows:
            for a in row.aux_keys:
                if a not in row.response_keys:
                    reg_finding(
                        "endpoint-contract-drift",
                        f"endpoint {row.endpoint!r}: aux key {a!r} is "
                        "not in the declared response key space — stale "
                        "aux entry",
                    )


# --------------------------------------------------------------------------
# check C: retry-unsafe-effect
# --------------------------------------------------------------------------


def _attr_leaf(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _attr_leaf(node.value)
    return None


def _guard_line(model: _WFile, fn: FuncNode) -> int | None:
    """First lexical consult of a dedup-guard attribute in ``fn``."""
    best: int | None = None
    for node in model.iter_scope(fn):
        if isinstance(node, ast.Attribute) and node.attr in _DEDUP_GUARDS:
            if best is None or node.lineno < best:
                best = node.lineno
    return best


def _effects(model: _WFile, fn: FuncNode,
             depth: int = 0,
             seen: "set[int] | None" = None) -> list[tuple[ast.AST, int, str]]:
    """(node, line-at-call-site, detail) side effects lexically reachable
    from ``fn``: attribute counter mutations, container mutations through
    an attribute receiver, cache writes, commit/seal calls — with
    same-file call propagation (effects in a callee count at the CALL's
    line, tier 4's idiom)."""
    if seen is None:
        seen = set()
    if id(fn) in seen or depth > 3:
        return []
    seen.add(id(fn))
    out: list[tuple[ast.AST, int, str]] = []
    for node in model.iter_scope(fn):
        if isinstance(node, ast.AugAssign):
            leaf = _attr_leaf(node.target)
            if leaf is not None and leaf not in _DEDUP_GUARDS:
                out.append((node, node.lineno, f"{leaf} mutation"))
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    leaf = _attr_leaf(t)
                    if leaf is not None and leaf not in _DEDUP_GUARDS:
                        out.append((node, node.lineno, f"{leaf}[...] write"))
        elif isinstance(node, ast.Call):
            cname = call_name(node) or ""
            leaf = cname.rsplit(".", 1)[-1]
            if leaf in _COMMIT_LEAVES:
                out.append((node, node.lineno, f"{leaf}() commit"))
            elif leaf in _MUTATOR_LEAVES and \
                    isinstance(node.func, ast.Attribute):
                recv_leaf = _attr_leaf(node.func.value)
                if recv_leaf is not None and recv_leaf not in _DEDUP_GUARDS:
                    out.append((node, node.lineno, f"{recv_leaf}.{leaf}()"))
    for call, callee in model.same_file_callees(fn):
        for _node, _line, detail in _effects(model, callee, depth + 1, seen):
            out.append((call, call.lineno, f"{detail} via "
                                           f"{callee.name}()"))
    return out


def _check_retry_safety(contract: WireContract, models: dict[str, _WFile],
                        sink: _Sink) -> None:
    for row in contract.rows:
        if "rid" not in row.request_keys:
            continue  # not a replayed route
        resolved = _resolve_spec(models, row.handler)
        if resolved is None:
            continue  # stale-handler finding already emitted
        model, fn, _recv = resolved
        guard = _guard_line(model, fn)
        for node, line, detail in _effects(model, fn):
            if guard is None:
                sink.add(
                    model.ctx, "retry-unsafe-effect", node,
                    f"side effect ({detail}) in replayed endpoint "
                    f"{row.endpoint!r} whose handler never consults a "
                    "request-id dedup guard — a router re-dispatch "
                    "executes it twice",
                )
            elif line < guard:
                sink.add(
                    model.ctx, "retry-unsafe-effect", node,
                    f"side effect ({detail}) executes BEFORE the "
                    f"request-id dedup guard (line {guard}) in replayed "
                    f"endpoint {row.endpoint!r} — a duplicate rid "
                    "double-counts it; move it behind the replay check",
                )


# --------------------------------------------------------------------------
# check D: floor-monotonicity
# --------------------------------------------------------------------------


def _check_floor(models: dict[str, _WFile], sink: _Sink) -> None:
    for rel in sorted(models):
        model = models[rel]
        for name in sorted(_FLOOR_WRITERS):
            for fn in model.defs.get(name, []):
                calls = {
                    (call_name(n) or "").rsplit(".", 1)[-1]
                    for n in model.iter_scope(fn)
                    if isinstance(n, ast.Call)
                }
                if not (calls & _DURABLE_LEAVES):
                    sink.add(
                        model.ctx, "floor-monotonicity", fn,
                        f"{name}() writes the generation floor without "
                        "durable_replace — a torn floor file reads as 0 "
                        "and un-fences every pre-floor replica",
                        line=fn.lineno,
                    )
                for n in model.iter_scope(fn):
                    if isinstance(n, ast.Call) and \
                            call_name(n) == "os.replace":
                        sink.add(
                            model.ctx, "floor-monotonicity", n,
                            f"{name}() uses raw os.replace — the floor "
                            "is pointer-visible state; use "
                            "utils/checkpoint.durable_replace so no "
                            "replica can read an unsynced floor",
                        )
        # every `.floor` attribute store outside __init__ must ratchet up
        for node in ast.walk(model.ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "floor"):
                continue
            encl = model.ctx.enclosing_function(node)
            if encl is not None and encl.name == "__init__":
                continue  # initial load, not a ratchet step
            if _floor_store_guarded(model, node):
                continue
            sink.add(
                model.ctx, "floor-monotonicity", node,
                "store to a .floor attribute without an upward-"
                "comparison guard (if new > current, or max(...)) — the "
                "generation floor only ratchets up; a downward store "
                "re-admits pre-floor artifacts mid-roll",
            )


def _floor_store_guarded(model: _WFile, node: ast.Assign) -> bool:
    if isinstance(node.value, ast.Call):
        cname = call_name(node.value) or ""
        if cname.rsplit(".", 1)[-1] == "max":
            for arg in node.value.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Attribute) and \
                            sub.attr == "floor":
                        return True
    cur = model.ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.If, ast.While)):
            for sub in ast.walk(cur.test):
                if isinstance(sub, ast.Compare) and any(
                    isinstance(op, (ast.Gt, ast.GtE, ast.Lt, ast.LtE))
                    for op in sub.ops
                ):
                    mentions_floor = any(
                        isinstance(s, ast.Attribute) and s.attr == "floor"
                        for side in (sub.left, *sub.comparators)
                        for s in ast.walk(side)
                    )
                    if mentions_floor:
                        return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break
        cur = model.ctx.parents.get(cur)
    return False


# --------------------------------------------------------------------------
# message-space enumeration (the derived dynamic fixture set)
# --------------------------------------------------------------------------


def enumerate_message_space(
    root: Path | None = None,
    models: "dict[str, _WFile] | None" = None,
) -> list[dict]:
    """Every probe the conformance harness replays, derived from the
    declared contract plus the handler's lexical request parse: malformed
    syntax/shape, each required key dropped, an out-of-contract path and
    method, a duplicate request id, and a stale-generation floor.  Each
    probe lists the status codes a conforming endpoint may answer with
    (the dispatcher's 404/500 catch-alls are always admissible)."""
    root = root or repo_root()
    if models is None:
        models = build_models(root)
    contract = wire_contract(root)
    if contract is None:
        return []
    probes: list[dict] = []
    declared_paths = set()
    for row in contract.rows:
        declared_paths.add(row.path)
        codes = sorted(c for c, _cls in row.status_classes)
        success = sorted(c for c, cls in row.status_classes
                         if cls == "success")
        base = {"endpoint": row.endpoint, "method": row.method,
                "path": row.path}
        required: set[str] = set()
        optional: set[str] = set()
        resolved = _resolve_spec(models, row.handler)
        if resolved is not None and resolved[2] is not None:
            required, optional = _required_request_keys(
                resolved[0], resolved[1], resolved[2])
        if row.method == "POST" and row.request_keys:
            probes.append({**base, "kind": "malformed-syntax",
                           "body": "{not json", "expect": [400]})
            probes.append({**base, "kind": "malformed-shape",
                           "body": "[]", "expect": [400]})
            for k in sorted(required & set(row.request_keys)):
                probes.append({**base, "kind": f"missing-{k}",
                               "drop_key": k, "expect": [400]})
            for k in sorted(optional & set(row.request_keys)):
                probes.append({**base, "kind": f"optional-{k}",
                               "drop_key": k, "expect": success or codes})
            probes.append({**base, "kind": "undeclared-key",
                           "extra_key": "__undeclared__",
                           "expect": success or codes})
        # method flip: the (method, path) route vanishes -> dispatcher 404
        flip = "GET" if row.method == "POST" else "POST"
        probes.append({**base, "kind": "wrong-method", "method": flip,
                       "expect": [404]})
        if "rid" in row.request_keys:
            probes.append({**base, "kind": "duplicate-rid",
                           "expect": success or codes})
            # unconditional: every replayed route sits behind the
            # generation floor.  The answer must ALSO be in the row's
            # declared code set — so a contract that forgets to declare
            # 503 fails the harness here, not just the static check.
            probes.append({**base, "kind": "stale-floor",
                           "expect": [503]})
        probes.append({**base, "kind": "declared-codes", "codes": codes})
    probes.append({"endpoint": None, "method": "GET",
                   "path": "/__out_of_contract__", "kind": "unknown-path",
                   "expect": [404]})
    return probes


# --------------------------------------------------------------------------
# the tier-6 runner
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ProtoResult:
    findings: list[Finding]
    monitored: list[str]

    @property
    def ok(self) -> bool:
        return not self.findings


def run_protocol(
    root: Path | None = None,
    only_modules: "set[str] | None" = None,
    models: "dict[str, _WFile] | None" = None,
) -> ProtoResult:
    """Run the tier-6 wire-protocol analysis.

    The model is always built over the full declared wire surface — a
    contract has handlers and readers in different files — and
    ``only_modules`` only filters which files may report findings (the
    ``--changed-only`` fast path)."""
    root = root or repo_root()
    if models is None:
        models = build_models(root)
    contract = wire_contract(root)

    sink = _Sink()
    if contract is not None:
        _check_contract(contract, models, sink)
        _check_retry_safety(contract, models, sink)
    _check_floor(models, sink)

    findings = sink.findings
    if only_modules is not None:
        findings = [f for f in findings if f.path in only_modules]
    return ProtoResult(findings=assign_fingerprints(findings),
                       monitored=sorted(models))
