"""graftlint tier 3, active half: the autotuning profile contract (ISSUE 16).

The static cost model (analysis/cost.py) is tier 3's passive gate — it
verifies that the shipped constants respect the declared pad/intensity
budgets.  This module is the contract layer that lets tier 3 go *active*:
``analysis/registry.py TUNED_KNOBS`` declares the knob search space
(knob → candidate domain → affected registry entries),
``utils/config.py TUNABLE_DEFAULTS`` is the single source of hand-picked
defaults, and ``tools/autotune.py`` commits per-backend
``tuned_profile_<backend>.json`` optima that every runner resolves
through ``utils.config.load_tuned_profile``.  Two checks keep those
surfaces honest, on the shared findings/suppression/ratchet machinery:

- **profile-drift** — the committed profile artifacts vs the declared
  space, validated in both directions (the ``DONATED_CALLEES`` contract
  style): a profile knob no longer declared (stale), a missing or
  mismatched backend stamp, a tuned value outside its declared domain,
  a declared knob the profile never tuned — and the declaration itself
  vs TUNABLE_DEFAULTS and the entry-point registry (a searchable knob
  with no default, a default with no search space, an affected entry
  that does not exist).
- **untuned-knob-read** — a declared tunable read from a bare literal in
  ``models//parallel//serving//dataflow/`` instead of through the
  resolution ladder: a function-signature or dataclass-field default
  spelled as a number (the default-drift hazard — it diverges silently
  from TUNABLE_DEFAULTS), or a call-site keyword that re-states the
  default value literally (a re-tune cannot reach that site).

Like tiers 1/4/5 this is stdlib-only — pure AST over the registry, the
config table, and the scan surface; the JSON artifacts are read with
``json`` — so the checks run even when jax is broken, and first in the
tier-3 block (before the trace-based cost pass brings a runtime up).
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.concurrency import (
    _Sink,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import (
    FileContext,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    iter_python_files,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    assign_fingerprints,
)

PROFILE_RULES: dict[str, str] = {
    "profile-drift": (
        "a committed tuned_profile_<backend>.json drifted from the "
        "TUNED_KNOBS search-space contract (stale knob, missing/mismatched "
        "backend stamp, out-of-domain value, declared-but-untuned knob), "
        "or the contract itself drifted from TUNABLE_DEFAULTS / the "
        "entry-point registry"
    ),
    "untuned-knob-read": (
        "a declared tunable read from a bare literal in models//parallel//"
        "serving//dataflow/ — a signature/dataclass default not reading "
        "TUNABLE_DEFAULTS, or a call-site keyword duplicating the default "
        "value — so the tuned-profile resolution ladder cannot reach it"
    ),
}

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"
_REGISTRY_REL = f"{_PKG}/analysis/registry.py"
_CONFIG_REL = f"{_PKG}/utils/config.py"

# the directories whose knob reads must go through the resolution ladder
_SCAN_PREFIXES = (
    f"{_PKG}/models/",
    f"{_PKG}/parallel/",
    f"{_PKG}/serving/",
    f"{_PKG}/dataflow/",
)


# --------------------------------------------------------------------------
# the declared contract (parsed lexically, persistence.py style)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProfileContract:
    knobs: tuple  # rows (name, domain tuple of numbers, entry-name tuple)
    entry_names: frozenset  # EntryPoint(name=...) spellings in the registry
    defaults: dict  # TUNABLE_DEFAULTS: name -> number
    registry_ctx: "FileContext | None"
    config_ctx: "FileContext | None"
    knobs_line: int
    defaults_line: int


def _num(node: ast.AST) -> "int | float | None":
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _str_tuple(node: ast.AST) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _num_tuple(node: ast.AST) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            v = _num(e)
            if v is not None:
                out.append(v)
        return tuple(out)
    return ()


def _load_ctx(root: Path, rel: str) -> "FileContext | None":
    path = root / rel
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError):
        return None
    return FileContext(rel, source, tree, root=root)


def _parse_registry(ctx: FileContext) -> tuple:
    """(TUNED_KNOBS rows, declaration line, EntryPoint names)."""
    rows: tuple = ()
    line = 1
    names: set[str] = set()
    for node in ast.walk(ctx.tree):
        value: "ast.expr | None" = None
        name: "str | None" = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if name == "TUNED_KNOBS" and isinstance(value, (ast.Tuple, ast.List)):
            line = node.lineno
            parsed = []
            for row in value.elts:
                if not isinstance(row, (ast.Tuple, ast.List)) or \
                        len(row.elts) != 3:
                    continue
                knob = row.elts[0]
                if not (isinstance(knob, ast.Constant)
                        and isinstance(knob.value, str)):
                    continue
                parsed.append((knob.value,
                               _num_tuple(row.elts[1]),
                               _str_tuple(row.elts[2])))
            rows = tuple(parsed)
        if isinstance(node, ast.Call):
            fn = node.func
            leaf = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if leaf == "EntryPoint":
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        names.add(kw.value.value)
    return rows, line, frozenset(names)


def _parse_defaults(ctx: FileContext) -> tuple:
    """(TUNABLE_DEFAULTS mapping, declaration line)."""
    table: dict = {}
    line = 1
    for node in ast.walk(ctx.tree):
        value: "ast.expr | None" = None
        name: "str | None" = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if name == "TUNABLE_DEFAULTS" and isinstance(value, ast.Dict):
            line = node.lineno
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    num = _num(v)
                    if num is not None:
                        table[k.value] = num
    return table, line


_contract_cache: dict[str, "ProfileContract | None"] = {}


def profile_contract(root: Path) -> "ProfileContract | None":
    key = str(root)
    if key in _contract_cache:
        return _contract_cache[key]
    reg_ctx = _load_ctx(root, _REGISTRY_REL)
    cfg_ctx = _load_ctx(root, _CONFIG_REL)
    contract = None
    if reg_ctx is not None and cfg_ctx is not None:
        knobs, knobs_line, entry_names = _parse_registry(reg_ctx)
        defaults, defaults_line = _parse_defaults(cfg_ctx)
        if knobs or defaults:
            contract = ProfileContract(
                knobs=knobs, entry_names=entry_names, defaults=defaults,
                registry_ctx=reg_ctx, config_ctx=cfg_ctx,
                knobs_line=knobs_line, defaults_line=defaults_line,
            )
    _contract_cache[key] = contract
    return contract


# --------------------------------------------------------------------------
# committed profile artifacts
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProfileArtifact:
    relpath: str  # e.g. "tuned_profile_cpu.json"
    backend: str  # backend named by the FILENAME
    record: "dict | None"  # parsed JSON (None: unreadable)
    error: "str | None"


def discover_profiles(root: Path) -> list[ProfileArtifact]:
    out = []
    for path in sorted(root.glob("tuned_profile_*.json")):
        backend = path.stem[len("tuned_profile_"):]
        record: "dict | None" = None
        error: "str | None" = None
        try:
            parsed = json.loads(path.read_text(encoding="utf-8"))
            if isinstance(parsed, dict):
                record = parsed
            else:
                error = "top-level JSON value is not an object"
        except (OSError, json.JSONDecodeError) as exc:
            error = str(exc)
        out.append(ProfileArtifact(relpath=path.name, backend=backend,
                                   record=record, error=error))
    return out


# --------------------------------------------------------------------------
# the checks
# --------------------------------------------------------------------------


def _check_contract(contract: ProfileContract, sink: _Sink) -> None:
    """TUNED_KNOBS vs TUNABLE_DEFAULTS vs ENTRY_POINTS, both directions."""
    ctx = contract.registry_ctx
    if ctx is None:
        return
    seen: set[str] = set()
    for knob, domain, entries in contract.knobs:
        if knob in seen:
            sink.add(ctx, "profile-drift", None,
                     f"TUNED_KNOBS declares {knob!r} twice",
                     line=contract.knobs_line)
        seen.add(knob)
        if knob not in contract.defaults:
            sink.add(ctx, "profile-drift", None,
                     f"TUNED_KNOBS declares {knob!r} but utils/config.py "
                     "TUNABLE_DEFAULTS has no such default — the search "
                     "space and the fallback ladder drifted apart",
                     line=contract.knobs_line)
        if not domain:
            sink.add(ctx, "profile-drift", None,
                     f"TUNED_KNOBS declares {knob!r} with an empty (or "
                     "non-numeric) candidate domain",
                     line=contract.knobs_line)
        if not entries:
            sink.add(ctx, "profile-drift", None,
                     f"TUNED_KNOBS declares {knob!r} with no affected "
                     "registry entries — nothing prunes or scores it",
                     line=contract.knobs_line)
        for entry in entries:
            if contract.entry_names and entry not in contract.entry_names:
                sink.add(ctx, "profile-drift", None,
                         f"TUNED_KNOBS maps {knob!r} to registry entry "
                         f"{entry!r}, which ENTRY_POINTS does not define",
                         line=contract.knobs_line)
    cfg_ctx = contract.config_ctx
    if cfg_ctx is not None:
        for name in contract.defaults:
            if name not in seen:
                sink.add(cfg_ctx, "profile-drift", None,
                         f"TUNABLE_DEFAULTS entry {name!r} has no "
                         "TUNED_KNOBS row — a tunable with no declared "
                         "search space can never be re-tuned",
                         line=contract.defaults_line)


def _check_profile(contract: ProfileContract, prof: ProfileArtifact,
                   sink: _Sink) -> None:
    """One committed artifact vs the declared space."""
    ctx = contract.registry_ctx
    if ctx is None:
        return
    if prof.record is None:
        sink.add(ctx, "profile-drift", None,
                 f"{prof.relpath}: unreadable profile artifact "
                 f"({prof.error})",
                 path=prof.relpath, line=1)
        return
    stamped = prof.record.get("backend")
    if stamped is None:
        sink.add(ctx, "profile-drift", None,
                 f"{prof.relpath}: missing backend stamp — the provenance "
                 "guard cannot protect an unstamped artifact",
                 path=prof.relpath, line=1)
    elif str(stamped) != prof.backend:
        sink.add(ctx, "profile-drift", None,
                 f"{prof.relpath}: stamped backend {stamped!r} does not "
                 f"match the filename backend {prof.backend!r}",
                 path=prof.relpath, line=1)
    knobs = prof.record.get("knobs")
    if not isinstance(knobs, dict):
        sink.add(ctx, "profile-drift", None,
                 f"{prof.relpath}: no 'knobs' mapping",
                 path=prof.relpath, line=1)
        return
    declared = {row[0]: row[1] for row in contract.knobs}
    for name, value in sorted(knobs.items()):
        if name not in declared:
            sink.add(ctx, "profile-drift", None,
                     f"{prof.relpath}: stale knob {name!r} — not declared "
                     "in TUNED_KNOBS (remove it or re-declare the knob)",
                     path=prof.relpath, line=1)
            continue
        domain = declared[name]
        default = contract.defaults.get(name)
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            sink.add(ctx, "profile-drift", None,
                     f"{prof.relpath}: knob {name!r} value {value!r} is "
                     "not a number",
                     path=prof.relpath, line=1)
        elif value not in domain and value != default:
            sink.add(ctx, "profile-drift", None,
                     f"{prof.relpath}: knob {name!r}={value!r} is outside "
                     f"its declared domain {list(domain)!r} (and is not "
                     "the TUNABLE_DEFAULTS value) — domain mismatch",
                     path=prof.relpath, line=1)
    for name in declared:
        if name not in knobs:
            sink.add(ctx, "profile-drift", None,
                     f"{prof.relpath}: declared tunable {name!r} is "
                     "untuned (absent from the profile) — the tuner "
                     "writes every declared knob, so an absence means "
                     "the artifact predates the declaration",
                     path=prof.relpath, line=1)


def _iter_defaults(fn: ast.AST):
    """(param name, default expr) pairs of a function definition."""
    args = fn.args
    pos = list(getattr(args, "posonlyargs", [])) + list(args.args)
    for param, default in zip(pos[len(pos) - len(args.defaults):],
                              args.defaults):
        yield param.arg, default
    for param, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            yield param.arg, default


def _check_knob_reads(contract: ProfileContract, ctx: FileContext,
                      sink: _Sink) -> None:
    """untuned-knob-read over one scanned file."""
    knob_names = set(contract.defaults) | {row[0] for row in contract.knobs}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for name, default in _iter_defaults(node):
                if name in knob_names and _num(default) is not None:
                    sink.add(
                        ctx, "untuned-knob-read", default,
                        f"tunable {name!r} defaults to the bare literal "
                        f"{_num(default)!r} here — read utils/config."
                        "TUNABLE_DEFAULTS (and resolve runs through "
                        "load_tuned_profile/tuned_config) so the default "
                        "cannot drift and a tuned profile can reach it",
                    )
        elif isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name) and \
                        stmt.target.id in knob_names and \
                        stmt.value is not None and \
                        _num(stmt.value) is not None:
                    sink.add(
                        ctx, "untuned-knob-read", stmt.value,
                        f"tunable field {stmt.target.id!r} defaults to the "
                        f"bare literal {_num(stmt.value)!r} — read "
                        "utils/config.TUNABLE_DEFAULTS so the dataclass "
                        "default and the tuner's table cannot drift",
                    )
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in knob_names:
                    val = _num(kw.value)
                    if val is not None and \
                            val == contract.defaults.get(kw.arg):
                        sink.add(
                            ctx, "untuned-knob-read", kw.value,
                            f"call re-states tunable {kw.arg!r}="
                            f"{val!r}, duplicating its TUNABLE_DEFAULTS "
                            "value as a literal — read the table (or omit "
                            "the argument) so a re-tune reaches this site",
                        )


# --------------------------------------------------------------------------
# report + runner
# --------------------------------------------------------------------------


def build_report(contract: "ProfileContract | None",
                 profiles: list[ProfileArtifact]) -> dict:
    """Declared-vs-tuned-vs-default, per knob per backend — what
    ``--profile-report`` renders."""
    if contract is None:
        return {}
    tuned: dict = {}
    meta: dict = {}
    for prof in profiles:
        knobs = (prof.record or {}).get("knobs")
        tuned[prof.backend] = knobs if isinstance(knobs, dict) else {}
        meta[prof.backend] = {
            "path": prof.relpath,
            "git_sha": (prof.record or {}).get("git_sha"),
            "created_wall": (prof.record or {}).get("created_wall"),
            "error": prof.error,
        }
    knob_rows = {}
    for knob, domain, entries in contract.knobs:
        knob_rows[knob] = {
            "default": contract.defaults.get(knob),
            "domain": list(domain),
            "entries": list(entries),
            "tuned": {b: tuned[b].get(knob) for b in sorted(tuned)},
        }
    return {"knobs": knob_rows, "profiles": meta}


@dataclasses.dataclass
class ProfileResult:
    findings: list
    report: dict

    @property
    def ok(self) -> bool:
        return not self.findings


def run_profile(
    root: "Path | None" = None,
    paths: "list[Path] | None" = None,
    only_modules: "set[str] | None" = None,
    contract: "ProfileContract | None" = None,
    profiles: "list[ProfileArtifact] | None" = None,
) -> ProfileResult:
    """Run the tier-3 profile-contract checks.

    Like tiers 4/5 the contract is always validated whole — a restricted
    run (``only_modules``) only filters which files may report findings.
    ``contract``/``profiles`` injection exists for synthetic-fixture
    tests."""
    root = root or repo_root()
    if contract is None:
        contract = profile_contract(root)
    if contract is None:
        return ProfileResult(findings=[], report={})
    if profiles is None:
        profiles = discover_profiles(root)

    sink = _Sink()
    _check_contract(contract, sink)
    for prof in profiles:
        _check_profile(contract, prof, sink)

    targets = paths if paths is not None else [root / _PKG]
    for f in iter_python_files(targets):
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        if not rel.startswith(_SCAN_PREFIXES):
            continue
        ctx = _load_ctx(root, rel)
        if ctx is not None:
            _check_knob_reads(contract, ctx, sink)

    findings = sink.findings
    if only_modules is not None:
        findings = [f for f in findings if f.path in only_modules]
    return ProfileResult(findings=assign_fingerprints(findings),
                         report=build_report(contract, profiles))
