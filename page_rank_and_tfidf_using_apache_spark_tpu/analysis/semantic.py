"""graftlint tier 2: jaxpr-level semantic analysis of registered jit entry
points.

The lexical tier (rules.py) sees source text; this tier sees what JAX
*traces*.  Every :class:`~.registry.EntryPoint` is traced with
``jax.make_jaxpr`` on the CPU backend from abstract ``ShapeDtypeStruct``
inputs — no FLOPs, no device transfers, a few hundred ms per entry — and
four invariants are checked against the entry's declared budgets:

- **recompile-per-shape** — the entry's shape matrix (raw workload sizes
  run through the caller's real padding/bucketing policy) must collapse to
  at most ``max_compiles`` distinct trace signatures.  More means
  unpadded/unbucketed shapes reach jit and production recompiles per
  shape (the failure class that RTT-bound round 5's streaming bench).
- **implicit-promotion** — traced under ``enable_x64`` with inputs pinned
  f32/i32, the jaxpr must contain no 64-bit aval anywhere (equation
  outputs or closed-over consts).  A hit means an unpinned constructor or
  a weak-type widening that makes CPU-test (x64 on) and TPU-prod (x64
  off) execute different dtypes.
- **transfer-census** — host-callback equations (``pure_callback`` /
  ``io_callback`` / ``debug_callback`` / infeed / outfeed) per traced
  step, gated against ``transfer_budget`` (default 0: a compiled step
  must never round-trip to host — closing the loop the lexical
  ``unguarded-host-sync`` rule opened).
- **sharding-axis** — every collective's axis names must be declared in
  the entry's ``axes``, and the static count of communication equations
  per step must not exceed ``collective_budget`` (communication volume is
  gated at lint time, not discovered in a timed-out bench).

A registry entry that no longer builds/traces is itself a finding
(``entry-point-broken``): the registry is a contract, not a best effort.

Findings flow through the same fingerprint/baseline/ratchet machinery as
tier 1 — one baseline file, one gate.
"""

from __future__ import annotations

import inspect
import os
import sys
from pathlib import Path
from typing import Any, Iterable, Sequence

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
    ENTRY_POINTS,
    EntryPoint,
    Traceable,
    build_traceable,
)

SEMANTIC_RULES: dict[str, str] = {
    "recompile-per-shape": (
        "shape matrix produces more distinct jit trace signatures than the "
        "entry's max_compiles — unpadded/unbucketed shapes reach jit"
    ),
    "implicit-promotion": (
        "64-bit aval inside a jaxpr traced under x64 from pinned f32/i32 "
        "inputs — an unpinned ctor or weak-type widening drifts dtypes "
        "between CPU tests and TPU production"
    ),
    "transfer-census": (
        "host-callback eqns per traced step exceed the entry's transfer "
        "budget — a compiled step must not round-trip to host"
    ),
    "sharding-axis": (
        "collective axis names outside the entry's declared mesh axes, or "
        "more communication eqns per step than its collective budget"
    ),
    "entry-point-broken": (
        "a registered jit entry point no longer builds or traces — the "
        "registry contract is stale"
    ),
    "collective-uniformity": (
        "a collective (psum/ppermute/all_gather/...) nested under a "
        "cond/while whose predicate depends on shard-varying operands — "
        "shards disagree about executing the collective, which is a "
        "deadlock on real hardware that CPU testing cannot reproduce"
    ),
}

# Primitives that cross the host boundary from inside a compiled program.
_CALLBACK_PRIMS = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "callback", "infeed", "outfeed"}
)

# Communication primitives (what collective_budget counts).  axis_index is
# checked for axis-name consistency but costs no bytes, so it is excluded
# from the budget.
_COMM_PRIMS = frozenset(
    {
        "psum",
        "pmax",
        "pmin",
        "ppermute",
        "all_gather",
        "all_to_all",
        "psum_scatter",
        "reduce_scatter",
    }
)
_AXIS_PRIMS = _COMM_PRIMS | {"axis_index"}

# Collectives whose OUTPUT is identical on every shard of the reduced
# axis: a predicate derived from one of these is uniform again, so the
# canonical `while err > tol` fixpoint (err = psum of shard residuals)
# stays clean under the collective-uniformity check.
_UNIFORMIZING_PRIMS = frozenset({"psum", "pmax", "pmin", "all_gather"})


def ensure_cpu_tracing_env() -> None:
    """Pin tracing to the CPU backend with simulated devices.

    Must run before the first ``import jax`` to take full effect; when jax
    is already imported (pytest, an embedding process) the config API still
    forces the platform, and the mesh builders adapt to however many
    devices exist.
    """
    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # already fixed by a plugin; tracing still works
        pass


def _iter_subjaxprs(value: Any) -> Iterable[Any]:
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):  # ClosedJaxpr
        yield value.jaxpr
    elif hasattr(value, "eqns"):  # raw Jaxpr
        yield value
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _iter_subjaxprs(item)


def walk_eqns(jaxpr) -> list:
    """Every equation in ``jaxpr`` and its nested sub-jaxprs (pjit bodies,
    scan/while/cond branches, shard_map bodies ...)."""
    out: list = []
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            out.append(eqn)
            for v in eqn.params.values():
                stack.extend(_iter_subjaxprs(v))
    return out


def _is_literal(v) -> bool:
    return hasattr(v, "val")  # jax.core.Literal; Vars carry no .val


def _inner_jaxpr(value):
    return value.jaxpr if hasattr(value, "jaxpr") else value


def _subtree_comm_names(jaxpr) -> set[str]:
    return {
        e.primitive.name
        for e in walk_eqns(jaxpr)
        if e.primitive.name in _COMM_PRIMS
    }


def _propagate_varying(jaxpr, in_varying: list, in_shard: bool,
                       record) -> list:
    """Abstract interpretation of shard-varying-ness over ``jaxpr``.

    ``in_varying`` aligns with ``jaxpr.invars`` (True = the value may
    differ between shards).  Uniformizing collectives (psum/pmax/pmin/
    all_gather) launder varying-ness; ppermute/all_to_all/scatter
    variants and everything data-dependent propagate it.  Entering a
    ``shard_map`` body seeds every invar varying and arms ``in_shard``.
    At each ``cond``/``while`` met while armed, ``record(ctrl, comms,
    pred_varying)`` is called with the collectives its subtree contains
    — a varying predicate over a collective-bearing subtree is the
    deadlock this check exists for.  Conservative on unknown structure:
    unmatched sub-jaxpr arities degrade to any-in → all-varying, never
    to silence."""
    jr = _inner_jaxpr(jaxpr)
    vmap: dict = {}
    for v, tainted in zip(jr.invars, in_varying):
        vmap[v] = bool(tainted)
    for cv in jr.constvars:
        vmap[cv] = False  # closed-over consts are replicated

    def val(v) -> bool:
        return False if _is_literal(v) else vmap.get(v, False)

    for eqn in jr.eqns:
        name = eqn.primitive.name
        ins = [val(v) for v in eqn.invars]
        any_in = any(ins)

        if name == "shard_map":
            inner = _inner_jaxpr(eqn.params.get("jaxpr"))
            if inner is not None and hasattr(inner, "eqns"):
                _propagate_varying(
                    inner, [True] * len(inner.invars), True, record)
            for ov in eqn.outvars:  # per-shard results: varying
                vmap[ov] = True
            continue

        if name == "cond":
            pred_varying = ins[0] if ins else False
            branches = [
                _inner_jaxpr(b) for b in eqn.params.get("branches", ())
            ]
            comms: set[str] = set()
            out_any = [False] * len(eqn.outvars)
            for b in branches:
                comms |= _subtree_comm_names(b)
                inner_in = ins[1:]
                if len(b.invars) != len(inner_in):
                    inner_in = [any_in] * len(b.invars)
                bouts = _propagate_varying(b, inner_in, in_shard, record)
                out_any = [
                    a or (bouts[i] if i < len(bouts) else any_in)
                    for i, a in enumerate(out_any)
                ]
            if in_shard and comms:
                record("cond", comms, pred_varying)
            for ov, tainted in zip(eqn.outvars, out_any):
                vmap[ov] = tainted or pred_varying
            continue

        if name == "while":
            cj = _inner_jaxpr(eqn.params["cond_jaxpr"])
            bj = _inner_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            cond_consts = ins[:cn]
            body_consts = ins[cn:cn + bn]
            carry = list(ins[cn + bn:])
            # fixpoint: body may widen carry varying-ness across trips
            for _ in range(len(carry) + 2):
                bouts = _propagate_varying(
                    bj, body_consts + carry, in_shard, lambda *a: None)
                if len(bouts) != len(carry):
                    bouts = [any(bouts) or any_in] * len(carry)
                widened = [c or b for c, b in zip(carry, bouts)]
                if widened == carry:
                    break
                carry = widened
            couts = _propagate_varying(
                cj, cond_consts + carry, in_shard, record)
            pred_varying = any(couts)
            comms = _subtree_comm_names(cj) | _subtree_comm_names(bj)
            if in_shard and comms:
                record("while", comms, pred_varying)
            # recurse once more with the real recorder for NESTED ctrl
            _propagate_varying(bj, body_consts + carry, in_shard, record)
            for ov, tainted in zip(eqn.outvars, carry):
                vmap[ov] = tainted or pred_varying
            continue

        subs = []
        for v in eqn.params.values():
            subs.extend(_iter_subjaxprs(v))
        if subs:
            souts: list = []
            for sj in subs:
                inner_in = (
                    ins if len(sj.invars) == len(eqn.invars)
                    else [any_in] * len(sj.invars)
                )
                souts = _propagate_varying(sj, inner_in, in_shard, record)
            if len(subs) == 1 and len(souts) == len(eqn.outvars):
                for ov, tainted in zip(eqn.outvars, souts):
                    vmap[ov] = tainted
                continue
        out_val = False if name in _UNIFORMIZING_PRIMS else any_in
        for ov in eqn.outvars:
            vmap[ov] = out_val
    return [val(v) for v in jr.outvars]


def _divergent_collectives(closed_jaxpr) -> set:
    """``(ctrl, comm-primitive)`` pairs for every collective nested under
    a ``cond``/``while`` (inside a shard_map scope) whose predicate the
    varying-ness propagation marks shard-varying."""
    hits: set = set()

    def record(ctrl: str, comms: set, pred_varying: bool) -> None:
        if pred_varying:
            for c in sorted(comms):
                hits.add((ctrl, c))

    jr = _inner_jaxpr(closed_jaxpr)
    _propagate_varying(jr, [False] * len(jr.invars), False, record)
    return hits


def _sixty_four_bit(dtype) -> bool:
    import numpy as np

    try:
        return np.dtype(dtype).itemsize == 8
    except TypeError:
        return False


def _aval_dtype(v):
    return getattr(getattr(v, "aval", None), "dtype", None)


def _trace_signature(jax, args: tuple) -> tuple:
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(args)
    )


def _eqn_axis_names(eqn) -> set[str]:
    names: set[str] = set()
    for key in ("axes", "axis_name"):
        v = eqn.params.get(key)
        if isinstance(v, str):
            names.add(v)
        elif isinstance(v, (list, tuple)):
            names.update(x for x in v if isinstance(x, str))
    return names


def _anchor_location(ep: EntryPoint, t: Traceable | None, root: Path) -> tuple[str, int, str]:
    """(repo-relative path, line, snippet) findings for this entry carry.
    Anchored at the entry's public function so fingerprints survive registry
    reshuffles; falls back to the declared module at line 1."""
    anchor = None
    if t is not None:
        anchor = t.anchor or t.fn
    path, line = ep.module, 1
    if anchor is not None:
        target = inspect.unwrap(anchor)
        try:
            src = Path(inspect.getsourcefile(target) or "")
            _, line = inspect.getsourcelines(target)
            path = src.resolve().relative_to(root.resolve()).as_posix()
        except (TypeError, OSError, ValueError):
            path, line = ep.module, 1
    snippet = ""
    full = root / path
    if full.exists():
        lines = full.read_text(encoding="utf-8").splitlines()
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
    return path, line, snippet


def _x64_context():
    from jax.experimental import enable_x64

    return enable_x64()


def _analyze_entry(ep: EntryPoint, root: Path) -> list[Finding]:
    import jax

    findings: list[Finding] = []

    def add(rule: str, message: str, t: Traceable | None) -> None:
        if rule in ep.suppress:
            return
        path, line, snippet = _anchor_location(ep, t, root)
        findings.append(
            Finding(
                rule=rule,
                path=path,
                line=line,
                col=0,
                message=f"[{ep.name}] {message}",
                snippet=snippet,
            )
        )

    try:
        t = build_traceable(ep)
    except Exception as exc:  # registry drifted from the code
        add(
            "entry-point-broken",
            f"entry point failed to build: {type(exc).__name__}: {exc}",
            None,
        )
        return findings

    # ---- recompile-per-shape: distinct signatures across the matrix
    sigs: dict[tuple, tuple[str, tuple]] = {}
    for label, args in t.variants:
        sigs.setdefault(_trace_signature(jax, args), (label, args))
    if len(sigs) > ep.max_compiles:
        labels = sorted(label for label, _ in sigs.values())
        add(
            "recompile-per-shape",
            f"{len(t.variants)} declared workload shapes produce "
            f"{len(sigs)} distinct jit signatures (budget "
            f"{ep.max_compiles}): {', '.join(labels)} — pad/bucket the "
            "shapes feeding this entry point",
            t,
        )

    # ---- trace once per distinct signature; pool the jaxpr-level checks
    promo: set[tuple[str, str]] = set()
    worst_transfers: tuple[int, str] = (0, "")
    worst_comms: tuple[int, str] = (0, "")
    comm_counts: dict[str, int] = {}
    undeclared_axes: set[str] = set()
    divergent: dict[tuple, str] = {}  # (ctrl, comm) -> first variant label
    for label, args in sigs.values():
        try:
            with _x64_context():
                closed = jax.make_jaxpr(t.fn)(*args)
        except Exception as exc:
            add(
                "entry-point-broken",
                f"tracing variant {label!r} failed: {type(exc).__name__}: {exc}",
                t,
            )
            return findings
        eqns = walk_eqns(closed.jaxpr)

        if not ep.allow_64bit:
            for const in closed.consts:
                dt = getattr(const, "dtype", None)
                if dt is not None and _sixty_four_bit(dt):
                    promo.add(("const", str(dt)))
            for eqn in eqns:
                for v in eqn.outvars:
                    dt = _aval_dtype(v)
                    if dt is not None and _sixty_four_bit(dt):
                        promo.add((eqn.primitive.name, str(dt)))

        transfers = sum(1 for e in eqns if e.primitive.name in _CALLBACK_PRIMS)
        if transfers > worst_transfers[0]:
            worst_transfers = (transfers, label)

        comms = 0
        for eqn in eqns:
            if eqn.primitive.name in _AXIS_PRIMS:
                undeclared_axes.update(_eqn_axis_names(eqn) - set(ep.axes))
            if eqn.primitive.name in _COMM_PRIMS:
                comms += 1
                comm_counts[eqn.primitive.name] = (
                    comm_counts.get(eqn.primitive.name, 0) + 1
                )
        if comms > worst_comms[0]:
            worst_comms = (comms, label)

        if ep.axes:  # sharded entries only: uniformity is a mesh property
            for pair in _divergent_collectives(closed.jaxpr):
                divergent.setdefault(pair, label)

    if promo:
        detail = ", ".join(f"{p}:{d}" for p, d in sorted(promo))
        add(
            "implicit-promotion",
            f"64-bit avals under x64 tracing from pinned 32-bit inputs: "
            f"{detail} — pin dtypes (dtype=jnp.int32/float32) at the "
            "flagged constructors",
            t,
        )

    if worst_transfers[0] > ep.transfer_budget:
        add(
            "transfer-census",
            f"{worst_transfers[0]} host-callback eqn(s) per step in variant "
            f"{worst_transfers[1]!r} (budget {ep.transfer_budget}) — a "
            "compiled step must not round-trip to host; hoist the callback "
            "out of the jit region or raise the budget with a review",
            t,
        )

    if undeclared_axes:
        add(
            "sharding-axis",
            f"collective axis name(s) {sorted(undeclared_axes)} not in the "
            f"declared mesh axes {list(ep.axes)} — the program and the "
            "registry disagree about the mesh contract",
            t,
        )
    if divergent:
        detail = ", ".join(
            f"{comm} under {ctrl} (variant {lbl!r})"
            for (ctrl, comm), lbl in sorted(divergent.items())
        )
        add(
            "collective-uniformity",
            f"collective(s) nested under shard-divergent control flow: "
            f"{detail} — shards disagree about executing the collective; "
            "on TPU this deadlocks the mesh (JAMPI's barrier-execution "
            "argument). Hoist the collective out of the branch/loop or "
            "make the predicate uniform (reduce it with psum/pmax first)",
            t,
        )

    if ep.collective_budget is not None and worst_comms[0] > ep.collective_budget:
        detail = ", ".join(f"{k}×{v}" for k, v in sorted(comm_counts.items()))
        add(
            "sharding-axis",
            f"{worst_comms[0]} communication eqn(s) per step in variant "
            f"{worst_comms[1]!r} (budget {ep.collective_budget}; {detail}) "
            "— extra collectives entered the step; fuse or re-budget with "
            "a review",
            t,
        )
    return findings


def run_semantic(
    root: Path | None = None,
    entries: Sequence[EntryPoint] | None = None,
    only_modules: set[str] | None = None,
) -> list[Finding]:
    """Trace and check registered entry points; returns fingerprinted
    findings (empty list == tier 2 clean).

    ``only_modules`` (repo-relative paths) restricts the run to entries
    whose contracted module — or any module on its ``watch`` list (shape
    policies, mesh constants) — is in the set: the ``--changed-only`` fast
    path.  When any ``analysis/`` file changed, pass None: the checker
    itself changed, so every contract gets re-verified.
    """
    from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import repo_root

    root = root or repo_root()
    ensure_cpu_tracing_env()
    findings: list[Finding] = []
    for ep in entries if entries is not None else ENTRY_POINTS:
        if only_modules is not None and not (
            {ep.module, *ep.watch} & only_modules
        ):
            continue
        findings.extend(_analyze_entry(ep, root))
    return assign_fingerprints(findings)
