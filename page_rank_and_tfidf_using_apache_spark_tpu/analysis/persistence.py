"""graftlint tier 5: persistence & crash-consistency analysis (ISSUE 14).

Spark delegates its durability discipline to HDFS rename semantics and
write-ahead logs: a task commits by renaming a completed attempt
directory into place, and the streaming receiver's WAL makes a committed
batch survive any executor death.  This runtime owns that discipline
itself — versioned array-dirs, segment manifests, atomic LATEST flips,
generation-deferred GC — and since ISSUE 13 the disk state is
load-bearing for *serving*: a torn commit is not a failed batch job, it
is a corrupted live index.  Tier 5 is the static gate for the
crash-window defect class.  Like tiers 1 and 4 it is stdlib-only — pure
AST, no jax import, whole-repo well under the declared
``GRAFT_PERSIST_BUDGET_S`` budget — and builds ONE repo-wide model of
every on-disk protocol (tempfile staging, fsyncs, ``os.replace``
renames, pointer flips, deletions, commit locks, the declared artifact
schemas) with same-file call propagation:

- **atomic-write-drift** — a file write that lands at its final name
  (no tempfile staging + atomic rename) tears under SIGKILL; and a raw
  ``os.replace`` on a *pointer-visible* path (the enclosing function
  flips — or is — a LATEST/manifest pointer) must instead go through the
  blessed ``utils/checkpoint.durable_replace`` idiom, which fsyncs the
  payload (file, or staged dir plus members) before the rename and the
  parent directory after it: a pointer must never be able to name
  unsynced data.  Append-mode writes (the JSONL event log) are exempt —
  append-only is the other crash-safe idiom.
- **pointer-flip-order** — a pointer flip may only name payloads whose
  commits precede it: any payload rename *after* a flip in the same
  protocol function means a reader resolving the new pointer races the
  payload landing (the flip must be the LAST durable act of a commit).
- **gc-before-flip** — deleting a non-staged path (``shutil.rmtree`` /
  ``os.unlink`` of a versioned dir, a snapshot, a replaced segment)
  before a later pointer flip in the same function destroys state the
  *current* generation still names; GC must be generation-deferred,
  reachable only after the flip that unnames its target (the
  SegmentMerger/commit_replace discipline).
- **schema-pair-drift** — ``analysis/registry.py ARTIFACT_SCHEMAS``
  declares each artifact family's key space (array members + META/JSON
  document keys) with its writer and reader functions; the lexical
  surface is validated both directions, the ``DONATED_CALLEES`` contract
  style: a declared key no writer stores, a non-aux key no reader loads
  (saved-but-never-loaded), and any write/read of an undeclared key are
  all findings — writer/reader schema drift is the "new build cannot
  load yesterday's index" class.
- **commit-lock-drift** — ``analysis/registry.py COMMIT_LOCKS`` declares
  the lock that serializes each protocol's read-modify-write commit
  (the segment manifest's ``_COMMIT_LOCK``); every lexical call to a
  protected mutator must sit under ``with <lock>``, and the declaration
  itself must not go stale.

The model also *derives* dynamic fixtures: :func:`enumerate_crash_points`
walks a commit function (expanding same-file and cross-protocol callees)
and lists every write boundary — payload writes, fsyncs, renames,
pointer flips, deletions — in execution order; the reader-visible ones
(``replace``/``delete``) are exactly the SIGKILL points
``tools/crash_harness.py`` replays, so new persistence code is
crash-tested by construction (``--crash-points`` on the CLI prints the
enumeration).

Findings flow through the same suppression (``# graftlint:
disable=<rule>``) and fingerprint/baseline/ratchet machinery as every
other tier.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterator

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.concurrency import (
    _Sink,
    _walk_own,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import (
    FileContext,
    FuncNode,
    call_name,
    dotted_name,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    default_targets,
    iter_python_files,
    repo_root,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    Finding,
    assign_fingerprints,
)

PERSIST_RULES: dict[str, str] = {
    "atomic-write-drift": (
        "a file write landing at its final name (no tempfile staging + "
        "atomic rename), or a raw os.replace on a pointer-visible path "
        "instead of the blessed durable_replace (fsync payload + parent "
        "dir) — a SIGKILL mid-write tears the artifact, or the pointer "
        "names unsynced data"
    ),
    "pointer-flip-order": (
        "a LATEST/manifest pointer flip precedes a payload commit in the "
        "same protocol function — a reader resolving the new pointer "
        "races the payload rename; the flip must be the last durable act"
    ),
    "gc-before-flip": (
        "a non-staged path is deleted before a later pointer flip in the "
        "same function — GC must be generation-deferred, reachable only "
        "after the flip that unnames its target"
    ),
    "schema-pair-drift": (
        "writer/reader drift against the declared ARTIFACT_SCHEMAS "
        "contract: a declared key nobody stores, a non-aux key nobody "
        "loads back, or a lexical write/read of an undeclared key"
    ),
    "commit-lock-drift": (
        "a declared commit-path mutator called without holding its "
        "COMMIT_LOCKS lock (manifest read-modify-write unserialized), or "
        "a stale lock/mutator declaration"
    ),
}

_PKG = "page_rank_and_tfidf_using_apache_spark_tpu"

_POINTER_FLIP_LEAVES = frozenset({"_write_pointer"})
_DURABLE_LEAVES = frozenset({"durable_replace"})
_FSYNC_LEAVES = frozenset({"fsync", "_fsync_path", "fsync_dir"})
_TMP_FACTORY_LEAVES = frozenset(
    {"mkstemp", "mkdtemp", "NamedTemporaryFile", "TemporaryDirectory"}
)
_DELETE_LEAVES = frozenset({"rmtree", "unlink", "remove", "rmdir"})
_DELETE_ROOTS = frozenset({"os", "shutil"})
# open()/os.fdopen() modes that create/truncate (append is exempt: an
# append-only log is the *other* crash-safe idiom)
_CREATE_MODE_CHARS = ("w", "x")

# Default crash-sequence entries for --crash-points: the commit paths
# whose write boundaries the harness replays.
CRASH_ENTRIES: tuple[str, ...] = (
    f"{_PKG}/serving/segments.py::commit_append",
    f"{_PKG}/serving/segments.py::commit_replace",
    f"{_PKG}/serving/segments.py::merge_segments",
    f"{_PKG}/serving/artifact.py::save_index",
    f"{_PKG}/utils/checkpoint.py::save_checkpoint",
    f"{_PKG}/serving/fabric.py::commit_floor",
)


# --------------------------------------------------------------------------
# the declared persistence contract (parsed lexically from the registry)
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PersistContract:
    schemas: tuple  # rows (family, writers, readers, keys, aux_keys)
    locks: tuple  # rows (module, lock name, protected callee leaves)
    relpath: str | None  # registry path when under the scanned root
    schemas_line: int
    locks_line: int


def _resolve_str(node: ast.AST, consts: dict[str, str]) -> str | None:
    """A string literal, a name bound to a module-level string constant,
    or an f-string over those (the registry's ``f"{_PKG}/..."`` idiom)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                inner = _resolve_str(v.value, consts)
                if inner is None:
                    return None
                parts.append(inner)
            else:
                return None
        return "".join(parts)
    return None


def _literal_strings(node: ast.AST, consts: dict[str, str]) -> tuple:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = _resolve_str(e, consts)
            if s is not None:
                out.append(s)
        return tuple(out)
    return ()


def _parse_contract_file(path: Path) -> tuple | None:
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    consts: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Constant) and \
                isinstance(stmt.value.value, str):
            consts[stmt.targets[0].id] = stmt.value.value
    schemas: tuple = ()
    locks: tuple = ()
    schemas_line = 1
    locks_line = 1
    for node in ast.walk(tree):
        value: ast.expr | None = None
        name: str | None = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if value is None or not isinstance(value, (ast.Tuple, ast.List)):
            continue
        if name == "ARTIFACT_SCHEMAS":
            schemas_line = node.lineno
            rows = []
            for row in value.elts:
                if not isinstance(row, (ast.Tuple, ast.List)) or \
                        len(row.elts) != 5:
                    continue
                fam = _resolve_str(row.elts[0], consts)
                if fam is None:
                    continue
                rows.append((
                    fam,
                    _literal_strings(row.elts[1], consts),
                    _literal_strings(row.elts[2], consts),
                    _literal_strings(row.elts[3], consts),
                    _literal_strings(row.elts[4], consts),
                ))
            schemas = tuple(rows)
        elif name == "COMMIT_LOCKS":
            locks_line = node.lineno
            rows = []
            for row in value.elts:
                if not isinstance(row, (ast.Tuple, ast.List)) or \
                        len(row.elts) != 3:
                    continue
                mod = _resolve_str(row.elts[0], consts)
                lock = _resolve_str(row.elts[1], consts)
                if mod is None or lock is None:
                    continue
                rows.append((mod, lock,
                             _literal_strings(row.elts[2], consts)))
            locks = tuple(rows)
    return schemas, locks, schemas_line, locks_line


_contract_cache: dict[str, PersistContract | None] = {}


def persist_contract(root: Path) -> PersistContract | None:
    key = str(root)
    if key in _contract_cache:
        return _contract_cache[key]
    candidates = [
        (root / f"{_PKG}/analysis/registry.py", True),
        (root / "analysis/registry.py", True),
        (Path(__file__).resolve().parent / "registry.py", False),
    ]
    contract = None
    for path, in_root in candidates:
        if path.exists():
            parsed = _parse_contract_file(path)
            if parsed is None:
                continue
            schemas, locks, schemas_line, locks_line = parsed
            relpath = None
            if in_root:
                try:
                    relpath = path.resolve().relative_to(
                        root.resolve()).as_posix()
                except ValueError:
                    relpath = path.as_posix()
            contract = PersistContract(
                schemas=schemas, locks=locks, relpath=relpath,
                schemas_line=schemas_line, locks_line=locks_line,
            )
            break
    _contract_cache[key] = contract
    return contract


# --------------------------------------------------------------------------
# per-file model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _Event:
    kind: str  # write | replace | durable | flip | fsync | delete | stage
    node: ast.AST
    line: int
    tainted: bool  # target derives from a tempfile staging name
    detail: str = ""


def _expr_mentions(expr: ast.AST | None, names: set[str]) -> bool:
    if expr is None:
        return False
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


def _target_names(tgt: ast.expr) -> Iterator[str]:
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            yield from _target_names(e)
    elif isinstance(tgt, ast.Starred):
        yield from _target_names(tgt.value)


class _PFile:
    """Per-file persistence facts."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.relpath = ctx.relpath
        self.defs: dict[str, list[FuncNode]] = {}
        self.def_class: dict[int, str | None] = {}  # id(fn) -> class name
        self.funcs: list[FuncNode] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
                self.funcs.append(node)
                cls = None
                cur = ctx.parents.get(node)
                while cur is not None:
                    if isinstance(cur, ast.ClassDef):
                        cls = cur.name
                        break
                    cur = ctx.parents.get(cur)
                self.def_class[id(node)] = cls
        # lazily-filled per-function caches
        self._taint: dict[int, set[str]] = {}
        self._handles: dict[int, set[str]] = {}
        self._events: dict[int, list[_Event]] = {}
        self.flipping: set[int] = set()
        self.deleting: set[int] = set()
        self._classify_functions()

    # ------------------------------------------------------------- helpers

    def resolve_def(self, funcpart: str) -> FuncNode | None:
        """Resolve ``name`` or ``Class.method`` to a def in this file."""
        cls = None
        name = funcpart
        if "." in funcpart:
            cls, name = funcpart.split(".", 1)
        for fn in self.defs.get(name, []):
            if cls is None or self.def_class.get(id(fn)) == cls:
                return fn
        return None

    def body_of(self, fn: FuncNode | None) -> list[ast.AST]:
        if fn is None:  # module level
            return list(self.ctx.tree.body)
        return fn.body if isinstance(fn.body, list) else [fn.body]

    def iter_scope(self, fn: FuncNode | None) -> Iterator[ast.AST]:
        """All nodes lexically in ``fn``'s own scope: the body statements,
        without descending into (or through) nested function definitions
        — those are scopes of their own and get their own pass."""
        for stmt in self.body_of(fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield from _walk_own(stmt)

    def tainted_names(self, fn: FuncNode | None) -> set[str]:
        key = id(fn)
        if key in self._taint:
            return self._taint[key]
        tainted: set[str] = set()
        nodes: list[ast.AST] = list(self.iter_scope(fn))
        for _ in range(2):  # fixpoint for straight-line chains
            for node in nodes:
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    value, targets = node.value, [node.target]
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        ce = item.context_expr
                        hit = _expr_mentions(ce, tainted) or (
                            isinstance(ce, ast.Call)
                            and self._is_tmp_factory(ce)
                        )
                        if hit and item.optional_vars is not None:
                            tainted.update(_target_names(item.optional_vars))
                    continue
                else:
                    continue
                hit = _expr_mentions(value, tainted) or (
                    isinstance(value, ast.Call)
                    and self._is_tmp_factory(value)
                )
                if hit:
                    for t in targets:
                        tainted.update(_target_names(t))
        self._taint[key] = tainted
        return tainted

    @staticmethod
    def _is_tmp_factory(call: ast.Call) -> bool:
        cname = call_name(call) or ""
        leaf = cname.rsplit(".", 1)[-1]
        root = cname[: -len(leaf) - 1] if "." in cname else ""
        return leaf in _TMP_FACTORY_LEAVES and root in ("", "tempfile", "tf")

    def handle_names(self, fn: FuncNode | None) -> set[str]:
        """Names bound as ``with open(...)/os.fdopen(...) as f`` in this
        scope — stream writes through them (json.dump, np.savez, .write)
        are covered by the classification of the open itself, so they are
        not reported a second time."""
        key = id(fn)
        cached = self._handles.get(key)
        if cached is not None:
            return cached
        handles: set[str] = set()
        for node in self.iter_scope(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call) and item.optional_vars is not None:
                        cname = call_name(ce) or ""
                        if cname.rsplit(".", 1)[-1] in ("open", "fdopen"):
                            handles.update(_target_names(item.optional_vars))
        self._handles[key] = handles
        return handles

    # ----------------------------------------------------------- event scan

    def _classify_call(self, node: ast.Call, tainted: set[str],
                       handles: "set[str] | None" = None) -> _Event | None:
        cname = call_name(node)
        leaf = cname.rsplit(".", 1)[-1] if cname else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None
        )
        if leaf is None:
            return None
        root = ""
        if cname is not None and "." in cname:
            root = cname[: -len(leaf) - 1]

        def ev(kind: str, target: ast.AST | None, detail: str = "") -> _Event:
            return _Event(kind=kind, node=node, line=node.lineno,
                          tainted=_expr_mentions(target, tainted),
                          detail=detail)

        if cname == "os.replace":
            return ev("replace", node.args[0] if node.args else None,
                      "os.replace")
        if leaf in _DURABLE_LEAVES:
            return ev("durable", node.args[0] if node.args else None,
                      "durable_replace")
        if leaf in _POINTER_FLIP_LEAVES:
            return ev("flip", None, "_write_pointer")
        if leaf in _FSYNC_LEAVES and root in ("", "os", "ckpt",
                                              "checkpoint"):
            return ev("fsync", None, leaf)
        if leaf in _DELETE_LEAVES and root in _DELETE_ROOTS | {""}:
            # a bare leaf must really be the os/shutil function, not a
            # list/set method: require a dotted os./shutil. spelling for
            # `remove`, allow bare rmtree/unlink (from-imports)
            if root == "" and leaf in ("remove", "rmdir"):
                return None
            return ev("delete", node.args[0] if node.args else None,
                      f"{cname or leaf}")
        if leaf in ("open", "fdopen"):
            if leaf == "fdopen" and root not in ("os", ""):
                return None
            if leaf == "open" and root not in ("", "io"):
                return None
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)):
                return None  # default "r" or dynamic: not a create-write
            if not any(c in mode.value for c in _CREATE_MODE_CHARS):
                return None
            return ev("write", node.args[0] if node.args else None,
                      f"{leaf}(mode={mode.value!r})")
        if leaf in ("write_text", "write_bytes") and \
                isinstance(node.func, ast.Attribute):
            return ev("write", node.func.value, leaf)
        if leaf in ("save", "savez", "savez_compressed") and \
                root in ("np", "numpy", "jnp"):
            target = node.args[0] if node.args else None
            if isinstance(target, ast.Name) and handles and \
                    target.id in handles:
                return None  # stream write: the open() carries the event
            return ev("write", target, f"{root}.{leaf}")
        if cname in ("json.dump",) and len(node.args) >= 2:
            target = node.args[1]
            if isinstance(target, ast.Name) and handles and \
                    target.id in handles:
                return None  # stream write: the open() carries the event
            return ev("write", target, "json.dump")
        if leaf in _TMP_FACTORY_LEAVES:
            return _Event(kind="stage", node=node, line=node.lineno,
                          tainted=True, detail=leaf)
        return None

    def events_of(self, fn: FuncNode | None) -> list[_Event]:
        key = id(fn)
        if key in self._events:
            return self._events[key]
        tainted = self.tainted_names(fn)
        handles = self.handle_names(fn)
        out: list[_Event] = []
        for node in self.iter_scope(fn):
            if isinstance(node, ast.Call):
                ev = self._classify_call(node, tainted, handles)
                if ev is not None:
                    out.append(ev)
        out.sort(key=lambda e: (e.line, getattr(e.node, "col_offset", 0)))
        self._events[key] = out
        return out

    def _classify_functions(self) -> None:
        """Fixpoint: a function that flips (or deletes) directly, or calls
        a same-file function that does, is flip-ish (delete-ish)."""
        direct_flip: set[int] = set()
        direct_del: set[int] = set()
        for fn in self.funcs:
            for ev in self.events_of(fn):
                if ev.kind == "flip":
                    direct_flip.add(id(fn))
                elif ev.kind == "delete" and not ev.tainted:
                    direct_del.add(id(fn))
        self.flipping = set(direct_flip)
        self.deleting = set(direct_del)
        changed = True
        while changed:
            changed = False
            for fn in self.funcs:
                if id(fn) in self.flipping and id(fn) in self.deleting:
                    continue
                for node in self.iter_scope(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = call_name(node)
                    leaf = cname.rsplit(".", 1)[-1] if cname else None
                    if leaf is None:
                        continue
                    for callee in self.defs.get(leaf, []):
                            if id(callee) in self.flipping and \
                                    id(fn) not in self.flipping:
                                self.flipping.add(id(fn))
                                changed = True
                            if id(callee) in self.deleting and \
                                    id(fn) not in self.deleting:
                                self.deleting.add(id(fn))
                                changed = True

    def flip_points(self, fn: FuncNode | None) -> list[_Event]:
        """Direct flips plus calls to same-file flip-ish functions, as
        events in lexical order."""
        out = [e for e in self.events_of(fn) if e.kind == "flip"]
        for node in self.iter_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            leaf = cname.rsplit(".", 1)[-1] if cname else None
            if leaf is None:
                continue
            for callee in self.defs.get(leaf, []):
                if id(callee) in self.flipping:
                    out.append(_Event(kind="flip", node=node,
                                      line=node.lineno, tainted=False,
                                      detail=f"{leaf}()"))
                    break
        out.sort(key=lambda e: (e.line, getattr(e.node, "col_offset", 0)))
        return out


# --------------------------------------------------------------------------
# monitored-module selection
# --------------------------------------------------------------------------


def _auto_persist(tree: ast.Module) -> bool:
    """A module is an on-disk protocol module when it renames into place
    or participates in the pointer-flip idiom — declared schema/lock
    modules are always included regardless."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            cname = call_name(node) or ""
            leaf = cname.rsplit(".", 1)[-1]
            if cname == "os.replace" or leaf in _POINTER_FLIP_LEAVES \
                    or leaf in _DURABLE_LEAVES:
                return True
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in _POINTER_FLIP_LEAVES | _DURABLE_LEAVES:
                return True
    return False


# --------------------------------------------------------------------------
# checks A-C: write/flip/GC discipline
# --------------------------------------------------------------------------


def _check_write_discipline(model: _PFile, sink: _Sink) -> None:
    ctx = model.ctx
    scopes: list[FuncNode | None] = [None, *model.funcs]
    for fn in scopes:
        fname = getattr(fn, "name", "<module>")
        blessed = fname in _DURABLE_LEAVES | _FSYNC_LEAVES
        events = model.events_of(fn)
        flipish = model.flip_points(fn)
        flip_lines = [e.line for e in flipish]
        fn_flips = bool(flipish) or fname in _POINTER_FLIP_LEAVES

        for ev in events:
            # A1: write landing at its final name
            if ev.kind == "write" and not ev.tainted:
                sink.add(
                    ctx, "atomic-write-drift", ev.node,
                    f"{ev.detail} lands at its final name — a SIGKILL "
                    "mid-write leaves a torn artifact a reader may open; "
                    "stage in a tempfile (mkstemp/mkdtemp) and "
                    "os.replace/durable_replace it into place",
                )
            # A2: raw rename on a pointer-visible path
            if ev.kind == "replace" and not blessed and fn_flips:
                sink.add(
                    ctx, "atomic-write-drift", ev.node,
                    "raw os.replace on a pointer-visible path (this "
                    "function participates in a pointer flip) — use "
                    "utils/checkpoint.durable_replace so the payload and "
                    "the parent directory are fsync'd before any pointer "
                    "can name them",
                )

        # B: flip before a later payload commit
        commits = [e for e in events if e.kind in ("replace", "durable")]
        for flip in flipish:
            late = [c for c in commits if c.line > flip.line]
            if late:
                sink.add(
                    ctx, "pointer-flip-order", flip.node,
                    f"pointer flip precedes a payload commit at line "
                    f"{late[0].line} — a reader resolving the new pointer "
                    "races the payload rename; commit every payload first, "
                    "flip last",
                )

        # C: deletion before a later flip
        deletes = [e for e in events if e.kind == "delete" and not e.tainted]
        for node in model.iter_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            leaf = cname.rsplit(".", 1)[-1] if cname else None
            if leaf is None:
                continue
            for callee in model.defs.get(leaf, []):
                if id(callee) in model.deleting:
                    deletes.append(_Event(
                        kind="delete", node=node, line=node.lineno,
                        tainted=False, detail=f"{leaf}()"))
                    break
        for d in deletes:
            later_flips = [ln for ln in flip_lines if ln > d.line]
            if later_flips:
                sink.add(
                    ctx, "gc-before-flip", d.node,
                    f"deletion ({d.detail}) precedes the pointer flip at "
                    f"line {later_flips[0]} — the current generation may "
                    "still name the target; defer GC until after the flip "
                    "that unnames it (the commit_replace discipline)",
                )


# --------------------------------------------------------------------------
# check D: schema-pair-drift
# --------------------------------------------------------------------------


def _split_spec(spec: str) -> tuple[str, str, str | None]:
    parts = spec.split("::")
    if len(parts) == 2:
        return parts[0], parts[1], None
    if len(parts) == 3:
        return parts[0], parts[1], parts[2]
    return spec, "", None


def _collect_written(model: _PFile, fn: FuncNode) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in model.iter_scope(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, k)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.slice, ast.Constant) and \
                        isinstance(t.slice.value, str):
                    out.setdefault(t.slice.value, t)
    return out


def _collect_read(model: _PFile, fn: FuncNode,
                  recv: str | None) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in model.iter_scope(fn):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            if recv is None or dotted_name(node.value) == recv:
                out.setdefault(node.slice.value, node)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            if recv is None or dotted_name(node.func.value) == recv:
                out.setdefault(node.args[0].value, node)
    return out


def _check_schemas(contract: PersistContract, models: dict[str, _PFile],
                   sink: _Sink) -> None:
    reg_model = models.get(contract.relpath) if contract.relpath else None

    def reg_finding(message: str, line: int) -> None:
        if reg_model is not None:
            sink.add(reg_model.ctx, "schema-pair-drift", None, message,
                     line=line)

    for family, writers, readers, keys, aux in contract.schemas:
        keyset = set(keys)
        for a in aux:
            if a not in keyset:
                reg_finding(
                    f"family {family!r}: aux key {a!r} is not in the "
                    "declared key space — stale aux entry",
                    contract.schemas_line,
                )
        written: dict[str, tuple[_PFile, ast.AST]] = {}
        read: dict[str, tuple[_PFile, ast.AST]] = {}
        for spec_list, collect, store in (
            (writers, _collect_written, written),
            (readers, _collect_read, read),
        ):
            for spec in spec_list:
                path, funcpart, recv = _split_spec(spec)
                model = models.get(path)
                fn = model.resolve_def(funcpart) if model else None
                if model is None or fn is None:
                    reg_finding(
                        f"family {family!r}: declared "
                        f"{'writer' if collect is _collect_written else 'reader'} "
                        f"{spec!r} does not resolve to a function on the "
                        "scan surface — stale contract row",
                        contract.schemas_line,
                    )
                    continue
                if collect is _collect_written:
                    got = _collect_written(model, fn)
                else:
                    got = _collect_read(model, fn, recv)
                for k, node in got.items():
                    store.setdefault(k, (model, node))
        if not written and not read:
            continue  # nothing resolved (restricted fixture tree)
        for k in keys:
            if k not in written:
                reg_finding(
                    f"family {family!r}: declared key {k!r} is stored by "
                    "no declared writer — the schema promises a member "
                    "the artifact never carries",
                    contract.schemas_line,
                )
            if k not in read and k not in aux:
                reg_finding(
                    f"family {family!r}: key {k!r} is saved but never "
                    "loaded by any declared reader — dead weight in every "
                    "artifact, or a reader lost a member it needs; mark "
                    "it aux (write-only forensics) or wire the reader",
                    contract.schemas_line,
                )
        for k, (model, node) in sorted(written.items()):
            if k not in keyset:
                sink.add(
                    model.ctx, "schema-pair-drift", node,
                    f"writer stores key {k!r} which family {family!r} "
                    "does not declare — add it to ARTIFACT_SCHEMAS (and a "
                    "reader, or mark it aux) before shipping it to disk",
                )
        for k, (model, node) in sorted(read.items()):
            if k not in keyset:
                sink.add(
                    model.ctx, "schema-pair-drift", node,
                    f"reader loads key {k!r} which family {family!r} does "
                    "not declare — a writer-side rename would break this "
                    "load path silently; declare the key",
                )


# --------------------------------------------------------------------------
# check E: commit-lock-drift
# --------------------------------------------------------------------------


def _lock_declared(model: _PFile, lockname: str) -> bool:
    for node in ast.walk(model.ctx.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == lockname
            for t in node.targets
        ) and isinstance(node.value, ast.Call):
            cname = call_name(node.value) or ""
            if cname.rsplit(".", 1)[-1] in ("Lock", "RLock"):
                return True
    return False


def _held_lock(ctx: FileContext, node: ast.AST, lockname: str) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                name = dotted_name(item.context_expr)
                if name is not None and (
                    name == lockname or name.endswith("." + lockname)
                ):
                    return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break
        cur = ctx.parents.get(cur)
    return False


def _check_commit_locks(contract: PersistContract,
                        models: dict[str, _PFile], sink: _Sink) -> None:
    reg_model = models.get(contract.relpath) if contract.relpath else None
    for module, lockname, callees in contract.locks:
        model = models.get(module)
        if model is None:
            if reg_model is not None:
                sink.add(
                    reg_model.ctx, "commit-lock-drift", None,
                    f"COMMIT_LOCKS names module {module!r} which is not "
                    "on the scan surface — stale declaration",
                    line=contract.locks_line,
                )
            continue
        if not _lock_declared(model, lockname):
            sink.add(
                model.ctx, "commit-lock-drift", None,
                f"COMMIT_LOCKS declares lock {lockname!r} for {module} "
                "but no threading.Lock/RLock of that name is defined "
                "there — stale declaration",
                line=1,
            )
        for callee in callees:
            if callee not in model.defs:
                if reg_model is not None:
                    sink.add(
                        reg_model.ctx, "commit-lock-drift", None,
                        f"COMMIT_LOCKS protects callee {callee!r} which "
                        f"{module} does not define — stale declaration",
                        line=contract.locks_line,
                    )
        callee_set = set(callees)
        for node in ast.walk(model.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            leaf = cname.rsplit(".", 1)[-1] if cname else None
            if leaf not in callee_set:
                continue
            if not _held_lock(model.ctx, node, lockname):
                sink.add(
                    model.ctx, "commit-lock-drift", node,
                    f"{leaf}() mutates commit state but is called without "
                    f"holding {lockname} — manifest read-modify-write "
                    "races another committer (an ingest seal and a merge "
                    "can resurrect each other's replaced segments); take "
                    "the declared commit lock",
                )


# --------------------------------------------------------------------------
# crash-point enumeration (the derived dynamic fixture set)
# --------------------------------------------------------------------------


def _leaf_index(models: dict[str, _PFile]) -> dict[str, tuple[_PFile, FuncNode]]:
    out: dict[str, tuple[_PFile, FuncNode]] = {}
    for rel in sorted(models):
        model = models[rel]
        for name, fns in model.defs.items():
            out.setdefault(name, (model, fns[0]))
    return out


def _enumerate_fn(model: _PFile, fn: FuncNode,
                  index: dict[str, tuple[_PFile, FuncNode]],
                  chain: tuple[str, ...], out: list[dict],
                  stack: set[str]) -> None:
    if len(chain) > 8:
        return
    tainted = model.tainted_names(fn)
    handles = model.handle_names(fn)
    calls: list[ast.Call] = [
        node for node in model.iter_scope(fn) if isinstance(node, ast.Call)
    ]
    calls.sort(key=lambda n: (n.lineno, n.col_offset))
    for node in calls:
        ev = model._classify_call(node, tainted, handles)
        cname = call_name(node)
        leaf = cname.rsplit(".", 1)[-1] if cname else None
        resolvable = (
            leaf in index and leaf is not None
            and not (isinstance(node.func, ast.Attribute)
                     and leaf in ("get", "put"))
        )
        if ev is not None and ev.kind in ("durable", "flip") and resolvable:
            ev = None  # expand the helper instead: its body holds the ops
        if ev is not None and ev.kind == "delete" and ev.tainted:
            # staging cleanup (the finally-block unlink of a tmp already
            # renamed away): guarded by exists(), never runs on the happy
            # path — not a reader-visible mutation, not a kill point
            ev = None
        if ev is not None and ev.kind != "stage":
            op = {"durable": "replace", "flip": "replace"}.get(ev.kind,
                                                               ev.kind)
            out.append({
                "seq": len(out),
                "op": op,
                "boundary": op in ("replace", "delete"),
                "path": model.relpath,
                "line": node.lineno,
                "via": " -> ".join(chain),
                "detail": ev.detail,
            })
            continue
        if resolvable:
            cmodel, cfn = index[leaf]
            key = f"{cmodel.relpath}::{leaf}"
            if key in stack:
                continue
            stack.add(key)
            _enumerate_fn(cmodel, cfn, index,
                          chain + (f"{leaf}()",), out, stack)
            stack.discard(key)


def build_models(root: Path,
                 paths: "list[Path] | None" = None) -> dict[str, _PFile]:
    """Parse the scan surface into per-file persistence models (all files
    are parsed — schema readers may live anywhere — but only protocol
    modules get the write-discipline checks)."""
    targets = paths if paths is not None else default_targets(root)
    models: dict[str, _PFile] = {}
    for f in iter_python_files(targets):
        try:
            source = f.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(f))
        except (OSError, SyntaxError):
            continue  # tier 1 reports parse errors
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        models[rel] = _PFile(FileContext(rel, source, tree, root=root))
    return models


def monitored_modules(contract: PersistContract | None,
                      models: dict[str, _PFile]) -> set[str]:
    monitored: set[str] = set()
    if contract is not None:
        for _family, writers, _readers, _keys, _aux in contract.schemas:
            for spec in writers:
                monitored.add(_split_spec(spec)[0])
        for module, _lock, _callees in contract.locks:
            monitored.add(module)
    for rel, model in models.items():
        if _auto_persist(model.ctx.tree):
            monitored.add(rel)
    return {m for m in monitored if m in models}


def enumerate_crash_points(
    root: Path | None = None,
    entry: str | None = None,
    models: "dict[str, _PFile] | None" = None,
) -> list[dict]:
    """Every write boundary of one commit sequence (``"<relpath>::<func>"``,
    default the first CRASH_ENTRIES entry), in execution order, with
    same-file and cross-protocol callees expanded.  Entries with
    ``boundary: true`` (renames and deletions — the reader-visible
    mutations) are the SIGKILL points ``tools/crash_harness.py`` replays."""
    root = root or repo_root()
    entry = entry or CRASH_ENTRIES[0]
    if models is None:
        models = build_models(root)
    contract = persist_contract(root)
    mon = monitored_modules(contract, models)
    index = _leaf_index({m: models[m] for m in mon})
    path, funcpart, _recv = _split_spec(entry)
    model = models.get(path)
    fn = model.resolve_def(funcpart) if model is not None else None
    if model is None or fn is None:
        raise ValueError(f"unknown crash entry {entry!r}")
    out: list[dict] = []
    _enumerate_fn(model, fn, index, (funcpart + "()",), out,
                  {f"{path}::{funcpart}"})
    return out


def crash_point_report(root: Path | None = None,
                       models: "dict[str, _PFile] | None" = None) -> dict:
    """{entry: [crash points]} for every default commit sequence —
    what ``--crash-points`` prints.  Pass ``models`` to reuse an
    already-built surface (the CLI shares one build with the findings
    pass, which is what the GRAFT_PERSIST_BUDGET_S gate times)."""
    root = root or repo_root()
    if models is None:
        models = build_models(root)
    report = {}
    for entry in CRASH_ENTRIES:
        try:
            report[entry] = enumerate_crash_points(root, entry, models)
        except ValueError:
            report[entry] = None  # entry not on this surface
    return report


# --------------------------------------------------------------------------
# the tier-5 runner
# --------------------------------------------------------------------------


@dataclasses.dataclass
class PersistResult:
    findings: list[Finding]
    monitored: list[str]

    @property
    def ok(self) -> bool:
        return not self.findings


def run_persistence(
    root: Path | None = None,
    paths: "list[Path] | None" = None,
    only_modules: "set[str] | None" = None,
    models: "dict[str, _PFile] | None" = None,
) -> PersistResult:
    """Run the tier-5 persistence analysis.

    Like tier 4, the repo-wide model is always built over the full scan
    surface — a schema has writers and readers in different files — and
    ``only_modules`` only filters which files may report findings (the
    ``--changed-only`` fast path).  ``models`` reuses a pre-built
    surface (see :func:`build_models`)."""
    root = root or repo_root()
    if models is None:
        models = build_models(root, paths)
    contract = persist_contract(root)
    mon = monitored_modules(contract, models)

    sink = _Sink()
    for rel in sorted(mon):
        _check_write_discipline(models[rel], sink)
    if contract is not None:
        _check_schemas(contract, models, sink)
        _check_commit_locks(contract, models, sink)

    findings = sink.findings
    if only_modules is not None:
        findings = [f for f in findings if f.path in only_modules]
    return PersistResult(findings=assign_fingerprints(findings),
                         monitored=sorted(mon))
