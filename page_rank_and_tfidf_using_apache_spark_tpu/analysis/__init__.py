"""graftlint — JAX/TPU-aware static analysis for this codebase (ISSUE 1).

The jit-compiled cores rest on invariants nothing else enforces: hot loops
stay inside one compiled program (no host round-trips), control flow on
traced values goes through lax combinators, dtypes are pinned (no float64
on TPU), shapes are static, and benchmarks fence what they time so XLA
cannot dead-code-eliminate the measured work.  ``analysis`` machine-checks
those invariants over the package, ``tools/`` and ``bench.py`` with a
ratchet baseline (``analysis/baseline.json``) so existing debt is frozen
and new violations fail CI (``tools/lint.sh``, ``tests/test_graftlint.py``).

Stdlib-only on purpose: the linter must keep working when jax is broken.
"""

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    apply_ratchet,
    baseline_path,
    default_targets,
    load_baseline,
    repo_root,
    run_lint,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import Finding
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import RULES

__all__ = [
    "Finding",
    "RULES",
    "apply_ratchet",
    "baseline_path",
    "default_targets",
    "load_baseline",
    "repo_root",
    "run_lint",
]
