"""graftlint — JAX/TPU-aware static analysis for this codebase (ISSUEs 1,
3, 6, 12, 14).

Five tiers over one ratchet baseline:

- **Tier 1 (lexical, rules.py)**: stdlib-only AST rules over the package,
  ``tools/`` and ``bench.py`` — hot loops stay inside one compiled program
  (no host round-trips), control flow on traced values goes through lax
  combinators, dtypes are pinned, shapes are static, benchmarks fence what
  they time, host syncs route through the resilience executor, thread
  targets take the lock, env knobs are declared.  Runs even when jax is
  broken.
- **Tier 2 (semantic, registry.py + semantic.py)**: traces every
  registered jit entry point on the CPU backend with ``jax.make_jaxpr``
  and checks what only the trace can show — recompile-per-shape across the
  declared shape matrix, 64-bit promotion under x64, host callbacks per
  compiled step, and collective axes/volume against the declared mesh
  contract.
- **Tier 3 (cost, cost.py)**: the static FLOP/byte model over the same
  traces — intensity floors (advisory while the cost artifacts are
  CPU-stamped), pad_frac budgets over the partition/padding plans, and
  the buffer-donation verifier against the lowered aliasing.
- **Tier 4 (concurrency, concurrency.py)**: stdlib-only interprocedural
  analysis of the threaded runtime — lock-order cycles,
  blocking-under-lock, use-after-donate over the ``DONATED_CALLEES``
  contract, chaos-coverage drift, thread/lock registry drift.
- **Tier 5 (persistence, persistence.py)**: stdlib-only crash-window
  analysis of every on-disk protocol — atomic-write drift, pointer-flip
  ordering, generation-deferred GC, writer/reader drift against
  ``ARTIFACT_SCHEMAS``, commit-lock drift against ``COMMIT_LOCKS`` — and
  the crash-point enumeration ``tools/crash_harness.py`` replays with
  SIGKILLs.

All tiers report through ``analysis/baseline.json`` (kept empty: fix true
positives, don't freeze them) and fail CI via ``tools/lint.sh`` and the
per-tier test files under ``tests/``.
"""

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.engine import (
    apply_ratchet,
    baseline_path,
    changed_python_files,
    default_targets,
    load_baseline,
    repo_root,
    run_lint,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import Finding
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import RULES

__all__ = [
    "Finding",
    "RULES",
    "apply_ratchet",
    "baseline_path",
    "changed_python_files",
    "default_targets",
    "load_baseline",
    "repo_root",
    "run_lint",
]
