"""graftlint CLI.

Usage::

    python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis \
        [paths...] [--tier 1|2|3|4|5|6|all] [--changed-only [BASE]] [--json] \
        [--baseline FILE | --no-baseline] [--write-baseline] \
        [--cost-report] [--profile-report] [--lock-graph] [--crash-points] \
        [--wire-probes] [--list-rules] [--list-entry-points]

Tier 1 is the lexical AST rule set (stdlib-only; runs even when jax is
broken).  Tier 2 traces the registered jit entry points on the CPU backend
and checks jaxpr-level invariants (recompile/promotion/transfer/sharding).
Tier 3 is the static cost model over the same traces: FLOP/byte
arithmetic-intensity floors (advisory while xla_cost_tpu.json is not
TPU-measured), static pad_frac budgets over the partition/padding plans,
and the buffer-donation verifier against the lowered aliasing.  Tier 4 is
the interprocedural concurrency & buffer-lifetime analyzer (stdlib-only
like tier 1): lock-order cycles, blocking calls under locks,
use-after-donate dataflow against the registry's donation-liveness
contract, chaos-coverage drift over the guarded sites, and thread/lock
drift against utils/config.py THREAD_REGISTRY; ``--lock-graph`` emits its
lock-acquisition graph as DOT (JSON under ``--json``).  Tier 5 is the
persistence & crash-consistency analyzer (stdlib-only like tiers 1/4):
atomic-write drift, pointer-flip ordering, generation-deferred GC,
writer/reader schema drift against ``analysis/registry.py``
``ARTIFACT_SCHEMAS``, and commit-lock drift against ``COMMIT_LOCKS``;
``--crash-points`` prints its enumeration of every write boundary in the
declared commit sequences (what ``tools/crash_harness.py`` replays with
SIGKILLs).  Tier 6 is the distributed wire-protocol analyzer
(stdlib-only like tiers 1/4/5): endpoint/status-code/key drift against
``analysis/registry.py`` ``WIRE_SCHEMAS``, status-class drift against
the router's retry logic, retry-unsafe side effects ahead of the
request-id dedup guard, and generation-floor monotonicity;
``--wire-probes`` prints its enumeration of the declared message space
(what ``tools/protocol_harness.py`` replays at a live replica).  Tiers
2 and 3 need an importable jax.  All tiers report through the same
ratchet baseline; tier-3 advisories are printed but never gate.

With no paths, tiers 1/4/5 scan the tier-1 surface (the package,
``tools/`` and ``bench.py``), tiers 2/3 cover every registered entry
point, and tier 6 models the declared wire surface.  With explicit
paths (or ``--changed-only``), tier 1 scans those files, tiers 2/3 run
only the entries whose contracted module is among them, and tiers 4/5/6
still model the whole surface but report only findings in those files —
unless an ``analysis/`` file itself changed, which re-verifies every
contract.

Exit codes: 0 = no findings beyond the ratchet baseline, 1 = new findings
(printed), 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import engine
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    render_human,
    render_json,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import RULES


def _relpaths(paths, root: Path) -> set[str]:
    out: set[str] = set()
    for f in engine.iter_python_files(paths):
        try:
            out.add(f.resolve().relative_to(root.resolve()).as_posix())
        except ValueError:
            out.add(f.as_posix())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to scan (default: package + tools + bench.py)")
    ap.add_argument("--tier", choices=("1", "2", "3", "4", "5", "6", "all"),
                    default="all",
                    help="1 = lexical rules, 2 = semantic (jaxpr) checks, "
                         "3 = static cost model (intensity/pad_frac/"
                         "donation), 4 = interprocedural concurrency & "
                         "buffer-lifetime analysis, 5 = persistence & "
                         "crash-consistency analysis, 6 = distributed "
                         "wire-protocol analysis, all = every tier "
                         "(default)")
    ap.add_argument("--cost-report", action="store_true",
                    help="print the tier-3 per-entry cost table as JSON "
                         "(implies the tier-3 analysis ran)")
    ap.add_argument("--profile-report", action="store_true",
                    help="print the tier-3 autotuning report as JSON — "
                         "declared domain vs tuned value vs hand-picked "
                         "default, per knob per backend (implies the "
                         "tier-3 analysis ran; this half is stdlib-only)")
    ap.add_argument("--lock-graph", action="store_true",
                    help="emit the tier-4 lock-acquisition graph as DOT "
                         "(embedded as JSON under --json); implies the "
                         "tier-4 analysis ran")
    ap.add_argument("--crash-points", action="store_true",
                    help="print the tier-5 crash-point enumeration (every "
                         "write boundary of the declared commit sequences) "
                         "as JSON; implies the tier-5 analysis ran")
    ap.add_argument("--wire-probes", action="store_true",
                    help="print the tier-6 message-space enumeration "
                         "(every malformed/out-of-contract/duplicate/"
                         "stale-floor probe the conformance harness "
                         "replays) as JSON; implies the tier-6 analysis "
                         "ran")
    ap.add_argument("--changed-only", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="lint only files changed vs BASE (default HEAD): "
                         "git worktree diff plus untracked files")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="ratchet file (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the ratchet; report every finding and fail on any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings into the baseline file "
                         "(new entries get an UNREVIEWED placeholder "
                         "justification you must edit)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-entry-points", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id:22s} [tier 1] {rule.summary}")
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.concurrency import (
            CONC_RULES,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.cost import (
            COST_RULES,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.persistence import (
            PERSIST_RULES,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.profile import (
            PROFILE_RULES,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.protocol import (
            PROTO_RULES,
        )
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.semantic import (
            SEMANTIC_RULES,
        )

        for rid, summary in SEMANTIC_RULES.items():
            print(f"{rid:22s} [tier 2] {summary}")
        for rid, summary in COST_RULES.items():
            print(f"{rid:22s} [tier 3] {summary}")
        for rid, summary in PROFILE_RULES.items():
            print(f"{rid:22s} [tier 3] {summary}")
        for rid, summary in CONC_RULES.items():
            print(f"{rid:22s} [tier 4] {summary}")
        for rid, summary in PERSIST_RULES.items():
            print(f"{rid:22s} [tier 5] {summary}")
        for rid, summary in PROTO_RULES.items():
            print(f"{rid:22s} [tier 6] {summary}")
        return 0

    if args.list_entry_points:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
            ENTRY_POINTS,
        )

        for ep in ENTRY_POINTS:
            axes = f" axes={list(ep.axes)}" if ep.axes else ""
            print(
                f"{ep.name:32s} {ep.module}{axes} "
                f"max_compiles={ep.max_compiles} "
                f"transfer_budget={ep.transfer_budget}"
            )
        return 0

    root = engine.repo_root()
    tier1 = args.tier in ("1", "all")
    tier2 = args.tier in ("2", "all")
    tier3 = args.tier in ("3", "all") or args.cost_report \
        or args.profile_report
    tier4 = args.tier in ("4", "all") or args.lock_graph
    tier5 = args.tier in ("5", "all") or args.crash_points
    tier6 = args.tier in ("6", "all") or args.wire_probes

    if args.changed_only is not None and args.paths:
        print("graftlint: give either paths or --changed-only, not both",
              file=sys.stderr)
        return 2

    restricted = False  # True when scanning a subset of the surface
    if args.changed_only is not None:
        try:
            changed = engine.changed_python_files(root, args.changed_only)
        except RuntimeError as exc:
            print(f"graftlint: {exc}", file=sys.stderr)
            return 2
        surface = set(engine.iter_python_files(engine.default_targets(root)))
        paths = [p for p in changed if p in surface]
        restricted = True
        if not paths:
            print("graftlint: no changed files on the lint surface — clean")
            return 0
    elif args.paths:
        missing = [p for p in args.paths if not p.exists()]
        if missing:
            print(f"graftlint: no such path: {missing[0]}", file=sys.stderr)
            return 2
        paths = list(args.paths)
        restricted = True
    else:
        paths = engine.default_targets(root)

    if args.write_baseline and args.tier != "all":
        # A single-tier write would carry over nothing for the other tier's
        # scanned files, silently deleting its justified entries.
        print("graftlint: --write-baseline requires --tier all (a partial "
              "write would wipe the other tier's baseline entries)",
              file=sys.stderr)
        return 2

    findings = engine.run_lint(paths, root) if tier1 else []

    scanned = _relpaths(paths, root)
    advisories: list = []
    cost_report: dict | None = None

    only_modules = None
    if restricted:
        # when the analyzer itself changed, every contract is suspect
        analyzer_changed = any(
            p.startswith(
                "page_rank_and_tfidf_using_apache_spark_tpu/analysis/"
            )
            for p in scanned
        )
        only_modules = None if analyzer_changed else scanned

    def _tier_unavailable(tier: int, exc: Exception) -> int:
        # Tier 1 must keep working when jax is broken; tiers 2/3 cannot.
        # Print what tier 1 found, then fail loudly with a distinct exit
        # code (2: gate unavailable, vs 1: findings) so callers like
        # bench.py can tell "dirty" from "could not check".
        if findings:
            print(render_human(findings), file=sys.stderr)
        print(
            f"graftlint: tier {tier} unavailable "
            f"({type(exc).__name__}: {exc}); rerun with --tier 1 to "
            "lint without jax",
            file=sys.stderr,
        )
        return 2

    if tier2:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis import semantic

        try:
            sem = semantic.run_semantic(root=root, only_modules=only_modules)
        except Exception as exc:
            return _tier_unavailable(2, exc)
        if sem:
            findings = engine.assign_fingerprints(list(findings) + sem)

    profile_report: dict | None = None
    if tier3:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
            cost,
            profile,
        )

        # the profile-contract half first: stdlib-only, so its findings
        # land even when the trace-based cost pass cannot bring jax up
        pres = profile.run_profile(root=root, only_modules=only_modules)
        if pres.findings:
            findings = engine.assign_fingerprints(
                list(findings) + pres.findings
            )
        profile_report = pres.report

        try:
            cres = cost.run_cost(root=root, only_modules=only_modules)
        except Exception as exc:
            return _tier_unavailable(3, exc)
        if cres.findings:
            findings = engine.assign_fingerprints(
                list(findings) + cres.findings
            )
        advisories = cres.advisories
        cost_report = cres.report

    lock_graph = None
    if tier4:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
            concurrency,
        )

        # interprocedural: always model the full surface; a restricted run
        # only filters which files may report findings
        cc = concurrency.run_concurrency(root=root, only_modules=only_modules)
        if cc.findings:
            findings = engine.assign_fingerprints(
                list(findings) + cc.findings
            )
        lock_graph = cc.graph

    crash_points = None
    if tier5:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
            persistence,
        )

        # like tier 4: always model the full surface; a restricted run
        # only filters which files may report findings.  One model build
        # serves both the findings pass and the crash-point enumeration
        # (the GRAFT_PERSIST_BUDGET_S ci gate times this invocation).
        pmodels = persistence.build_models(root)
        pres = persistence.run_persistence(root=root,
                                           only_modules=only_modules,
                                           models=pmodels)
        if pres.findings:
            findings = engine.assign_fingerprints(
                list(findings) + pres.findings
            )
        if args.crash_points:
            crash_points = persistence.crash_point_report(root,
                                                          models=pmodels)

    wire_probes = None
    if tier6:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis import (
            protocol,
        )

        # like tiers 4/5: always model the declared wire surface; a
        # restricted run only filters which files may report findings.
        # One model build serves both the findings pass and the probe
        # enumeration (the GRAFT_PROTO_BUDGET_S ci gate times this).
        wmodels = protocol.build_models(root)
        wres = protocol.run_protocol(root=root,
                                     only_modules=only_modules,
                                     models=wmodels)
        if wres.findings:
            findings = engine.assign_fingerprints(
                list(findings) + wres.findings
            )
        if args.wire_probes:
            wire_probes = protocol.enumerate_message_space(root,
                                                           models=wmodels)

    if tier2 or tier3:
        from page_rank_and_tfidf_using_apache_spark_tpu.analysis.registry import (
            ENTRY_POINTS,
        )

        # tier-2/3 findings anchor at their contracted modules: include
        # them in the written-baseline scan set so --write-baseline is
        # coherent
        scanned |= {
            ep.module
            for ep in ENTRY_POINTS
            if only_modules is None or ({ep.module, *ep.watch} & only_modules)
        }

    bl_path = args.baseline or engine.baseline_path(root)

    if args.write_baseline:
        engine.write_baseline(bl_path, findings, scanned_paths=scanned)
        print(
            f"graftlint: froze {len(findings)} finding(s) over "
            f"{len(scanned)} file(s) into {bl_path} (entries for unscanned "
            "files preserved)"
        )
        return 0

    baseline = {} if args.no_baseline else engine.load_baseline(bl_path)
    result = engine.apply_ratchet(findings, baseline)
    # Staleness is only decidable on a full scan with every tier: a
    # restricted or single-tier run never re-finds entries for files (or
    # rules) it did not look at.
    stale = [] if (restricted or args.tier != "all") else result.stale

    if args.cost_report and cost_report is not None and not args.json:
        import json as _json

        print(_json.dumps(cost_report, indent=2))

    if args.profile_report and profile_report is not None and not args.json:
        import json as _json

        print(_json.dumps(profile_report, indent=2))

    if args.lock_graph and lock_graph is not None and not args.json:
        print(lock_graph.to_dot())

    if args.crash_points and crash_points is not None and not args.json:
        import json as _json

        print(_json.dumps(crash_points, indent=2))

    if args.wire_probes and wire_probes is not None and not args.json:
        import json as _json

        print(_json.dumps(wire_probes, indent=2))

    if args.json:
        extra_json = {}
        if advisories:
            extra_json["advisories"] = [f.to_dict() for f in advisories]
        if args.cost_report and cost_report is not None:
            extra_json["cost_report"] = cost_report
        if args.profile_report and profile_report is not None:
            extra_json["profile_report"] = profile_report
        if args.lock_graph and lock_graph is not None:
            extra_json["lock_graph"] = lock_graph.to_json()
        if args.crash_points and crash_points is not None:
            extra_json["crash_points"] = crash_points
        if args.wire_probes and wire_probes is not None:
            extra_json["wire_probes"] = wire_probes
        print(
            render_json(
                result.new,
                known=len(result.known),
                stale=[e["fingerprint"] for e in stale],
                ok=result.ok,
                **extra_json,
            )
        )
    else:
        for f in advisories:
            print(f"graftlint: advisory (not gating): {f.render()}")
        if result.new:
            print(render_human(result.new))
            print(
                f"\ngraftlint: {len(result.new)} new finding(s) "
                f"({len(result.known)} baselined). Fix them, suppress with "
                "'# graftlint: disable=<rule>' (justify in review), or — "
                "outside hot paths — add to analysis/baseline.json with a "
                "justification."
            )
        else:
            print(
                f"graftlint: clean ({len(result.known)} baselined finding(s) "
                f"remain to burn down)"
            )
        for e in stale:
            print(
                f"graftlint: stale baseline entry {e['fingerprint']} "
                f"({e['rule']} at {e['path']}) — finding no longer exists; "
                "delete it from baseline.json"
            )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
