"""graftlint CLI.

Usage::

    python -m page_rank_and_tfidf_using_apache_spark_tpu.analysis \
        [paths...] [--json] [--baseline FILE | --no-baseline] \
        [--write-baseline] [--list-rules]

With no paths, scans the tier-1 surface: the package, ``tools/`` and
``bench.py``.  Exit codes: 0 = no findings beyond the ratchet baseline,
1 = new findings (printed), 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from page_rank_and_tfidf_using_apache_spark_tpu.analysis import engine
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.findings import (
    render_human,
    render_json,
)
from page_rank_and_tfidf_using_apache_spark_tpu.analysis.rules import RULES


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to scan (default: package + tools + bench.py)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="ratchet file (default: analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the ratchet; report every finding and fail on any")
    ap.add_argument("--write-baseline", action="store_true",
                    help="freeze current findings into the baseline file "
                         "(new entries get an UNREVIEWED placeholder "
                         "justification you must edit)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id:20s} {rule.summary}")
        return 0

    root = engine.repo_root()
    paths = args.paths or engine.default_targets(root)
    missing = [p for p in paths if not p.exists()]
    if missing:
        print(f"graftlint: no such path: {missing[0]}", file=sys.stderr)
        return 2

    findings = engine.run_lint(paths, root)
    bl_path = args.baseline or engine.baseline_path(root)

    if args.write_baseline:
        scanned = set()
        for f in engine.iter_python_files(paths):
            try:
                scanned.add(f.resolve().relative_to(root.resolve()).as_posix())
            except ValueError:
                scanned.add(f.as_posix())
        engine.write_baseline(bl_path, findings, scanned_paths=scanned)
        print(
            f"graftlint: froze {len(findings)} finding(s) over "
            f"{len(scanned)} file(s) into {bl_path} (entries for unscanned "
            "files preserved)"
        )
        return 0

    baseline = {} if args.no_baseline else engine.load_baseline(bl_path)
    result = engine.apply_ratchet(findings, baseline)

    if args.json:
        print(
            render_json(
                result.new,
                known=len(result.known),
                stale=[e["fingerprint"] for e in result.stale],
                ok=result.ok,
            )
        )
    else:
        if result.new:
            print(render_human(result.new))
            print(
                f"\ngraftlint: {len(result.new)} new finding(s) "
                f"({len(result.known)} baselined). Fix them, suppress with "
                "'# graftlint: disable=<rule>' (justify in review), or — "
                "outside hot paths — add to analysis/baseline.json with a "
                "justification."
            )
        else:
            print(
                f"graftlint: clean ({len(result.known)} baselined finding(s) "
                f"remain to burn down)"
            )
        for e in result.stale:
            print(
                f"graftlint: stale baseline entry {e['fingerprint']} "
                f"({e['rule']} at {e['path']}) — finding no longer exists; "
                "delete it from baseline.json"
            )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
