"""graftlint rule set: the five failure classes this codebase has actually
shipped (ISSUE 1, VERDICT.md rounds 1–5).

Each rule is a function ``check(ctx: FileContext) -> Iterator[(node, msg)]``
registered in ``RULES``.  Rules are lexical AST checks — deliberately cheap
and import-free — tuned for the invariants the jit-compiled cores depend
on: everything hot stays inside one compiled program, zero host round-trips
per iteration, static shapes, no float64 on TPU, and benchmarks that
measure work XLA cannot dead-code-eliminate.

Suppress a finding with a trailing ``# graftlint: disable=<rule-id>``
comment (comma-separate several ids, omit ``=...`` to disable all rules on
that line), or file-wide with ``# graftlint: disable-file=<rule-id>``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterator

from page_rank_and_tfidf_using_apache_spark_tpu.analysis.context import (
    FileContext,
    FuncNode,
    call_name,
    dotted_name,
)

Hit = tuple[ast.AST, str]
CheckFn = Callable[[FileContext], Iterator[Hit]]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    check: CheckFn


RULES: dict[str, Rule] = {}


def rule(rule_id: str, summary: str) -> Callable[[CheckFn], CheckFn]:
    def register(fn: CheckFn) -> CheckFn:
        RULES[rule_id] = Rule(rule_id, summary, fn)
        return fn

    return register


# --------------------------------------------------------------------------
# shared predicates
# --------------------------------------------------------------------------

_SYNC_METHOD_NAMES = frozenset({"block_until_ready", "item", "tolist"})
_SYNC_CALL_NAMES = frozenset(
    {
        "jax.device_get",
        "jax.block_until_ready",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
    }
)
_DEVICE_ROOTS = ("jnp.", "jax.", "lax.")


def _sync_kind(node: ast.Call, ctx: FileContext, traced: set[str] | None) -> str | None:
    """Classify a call as a host-sync construct, or None.

    ``float()``/``int()`` only count when the argument is device-flavored:
    a traced name (when taint is known) or an expression containing a
    jax/jnp call — ``float("inf")`` and config parsing stay quiet.
    """
    cname = call_name(node)
    if cname in _SYNC_CALL_NAMES:
        return cname
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHOD_NAMES:
        if node.func.attr == "item" and node.args:
            return None  # dict.item(...) lookalikes take args; x.item() doesn't
        return f".{node.func.attr}()"
    if cname in ("float", "int") and len(node.args) == 1:
        arg = node.args[0]
        if traced is not None and ctx.expr_is_traced(arg, traced):
            return f"{cname}()"
        if _contains_device_call(arg):
            return f"{cname}()"
    return None


# jax.* calls that only query topology/config — they return host objects,
# never device buffers, so they must not taint values as device-flavored.
_NON_DISPATCH_JAX = frozenset(
    {
        "jax.devices",
        "jax.local_devices",
        "jax.device_count",
        "jax.local_device_count",
        "jax.default_backend",
    }
)


def _contains_device_call(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname and cname in _NON_DISPATCH_JAX:
                continue
            if cname and (
                cname.startswith(_DEVICE_ROOTS) or cname in ("jnp", "jax")
            ):
                return True
    return False


def _is_device_dispatch(node: ast.Call, ctx: FileContext) -> bool:
    """A call that launches/transfers device work: jnp.*/jax.*/lax.* calls
    (minus the sync constructs) or calls to names bound to jit functions."""
    cname = call_name(node)
    if cname is None:
        return False
    if cname in _SYNC_CALL_NAMES:
        return False
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHOD_NAMES:
        return False
    if cname.startswith(_DEVICE_ROOTS):
        return True
    return cname in ctx.jit_value_names


def _walk_own_body(fn: FuncNode) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


# --------------------------------------------------------------------------
# 1. host-sync-in-loop
# --------------------------------------------------------------------------


@rule(
    "host-sync-in-loop",
    "host round-trip (block_until_ready / device_get / np.asarray / float / "
    ".item) inside a jit context or a device-dispatching Python loop",
)
def check_host_sync(ctx: FileContext) -> Iterator[Hit]:
    taint_cache: dict[FuncNode, set[str]] = {}

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        in_jit = ctx.in_jit_context(node)
        traced: set[str] | None = None
        if in_jit:
            fn = ctx.enclosing_function(node)
            if fn is not None:
                if fn not in taint_cache:
                    taint_cache[fn] = ctx.traced_names(fn)
                traced = taint_cache[fn]
        kind = _sync_kind(node, ctx, traced)
        if kind is None:
            continue

        if in_jit:
            yield (
                node,
                f"host sync {kind} inside jit-traced code — the value is a "
                "tracer here; hoist the transfer out of the compiled region",
            )
            continue

        # outside jit: a sync is hot-loop poison when the same Python loop
        # also dispatches device work — every iteration then pays a device
        # round-trip (the exact pattern that serializes the streaming path).
        for loop in ctx.enclosing_loops(node):
            dispatches = any(
                isinstance(n, ast.Call)
                and n is not node
                and _is_device_dispatch(n, ctx)
                for n in ast.walk(loop)
            )
            if dispatches:
                yield (
                    node,
                    f"host sync {kind} inside a Python loop that also "
                    "dispatches device work — each iteration pays a "
                    "host<->device round-trip; batch the transfer or move "
                    "the loop into lax.scan/fori_loop",
                )
                break


# --------------------------------------------------------------------------
# 2. tracer-branch
# --------------------------------------------------------------------------


@rule(
    "tracer-branch",
    "Python if/while on a traced value inside jit — trace-time "
    "ConcretizationError or silently trace-time-frozen branch",
)
def check_tracer_branch(ctx: FileContext) -> Iterator[Hit]:
    for fn in ctx.jit_context_funcs:
        traced = ctx.traced_names(fn)
        if not traced:
            continue
        for node in _walk_own_body(fn):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            else:
                continue
            if ctx.expr_is_traced(test, traced):
                kw = {
                    ast.If: "if",
                    ast.While: "while",
                    ast.IfExp: "conditional expression",
                }[type(node)]
                yield (
                    node,
                    f"Python `{kw}` on a traced value inside jit — use "
                    "jnp.where / lax.cond / lax.while_loop so the branch "
                    "stays inside the compiled program",
                )


# --------------------------------------------------------------------------
# 3. dtype-drift
# --------------------------------------------------------------------------

_FLOAT_DEFAULT_CTORS = frozenset({"zeros", "ones", "empty", "full", "linspace"})
_NP_ROOTS = ("np.", "numpy.")


def _has_dtype_arg(node: ast.Call, ctor: str) -> bool:
    if any(kw.arg == "dtype" for kw in node.keywords):
        return True
    if ctor == "linspace":
        return False  # dtype sits after retstep/axis — kwarg-only in practice
    # positional dtype: zeros/ones/empty take it 2nd, full 3rd
    pos = {"full": 2}.get(ctor, 1)
    return len(node.args) > pos


@rule(
    "dtype-drift",
    "float64 (explicit, or numpy/JAX float default with no dtype=) flowing "
    "toward device arrays — unsupported/slow on TPU, silently downcast "
    "elsewhere",
)
def check_dtype_drift(ctx: FileContext) -> Iterator[Hit]:
    for node in ast.walk(ctx.tree):
        # explicit float64 spellings
        if isinstance(node, ast.Attribute) and node.attr in ("float64", "double"):
            base = dotted_name(node.value)
            if base in ("np", "numpy", "jnp", "jax.numpy"):
                yield (
                    node,
                    f"explicit {base}.{node.attr} — TPU has no fast float64 "
                    "path; pin float32/bfloat16 (or gate behind a CPU-only "
                    "code path)",
                )
            continue
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname is None:
            continue
        for kw in node.keywords:
            if (
                kw.arg == "dtype"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value in ("float64", "f8", "double")
            ):
                yield (
                    node,
                    'dtype="float64" literal — TPU arrays must pin an '
                    "explicit 32-bit (or narrower) dtype",
                )

        leaf = cname.rsplit(".", 1)[-1]
        if leaf not in _FLOAT_DEFAULT_CTORS:
            continue
        if cname.startswith("jnp."):
            if not _has_dtype_arg(node, leaf):
                yield (
                    node,
                    f"jnp.{leaf} without dtype= — inherits the float default "
                    "(float64 under x64), so CPU-test and TPU-prod dtypes "
                    "drift; pass dtype explicitly",
                )
        elif cname.startswith(_NP_ROOTS):
            # np float64 default flowing straight into a device transfer
            parent = ctx.parents.get(node)
            feeding_device = (
                isinstance(parent, ast.Call)
                and (call_name(parent) or "").startswith(("jnp.", "jax."))
            )
            if feeding_device and not _has_dtype_arg(node, leaf):
                yield (
                    node,
                    f"np.{leaf} (float64 default) passed straight into a "
                    "jax/jnp call — the transfer silently downcasts (x64 "
                    "off) or plants float64 on device (x64 on); pass dtype=",
                )


# --------------------------------------------------------------------------
# 4. nonstatic-shape
# --------------------------------------------------------------------------

_DATA_DEPENDENT_CALLS = frozenset(
    {"nonzero", "flatnonzero", "argwhere", "unique", "compress"}
)


def _is_boolean_mask(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Compare):
        return True
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Invert):
        return _is_boolean_mask(expr.operand)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, (ast.BitAnd, ast.BitOr)):
        return _is_boolean_mask(expr.left) or _is_boolean_mask(expr.right)
    return False


@rule(
    "nonstatic-shape",
    "data-dependent output shape inside jit (boolean-mask indexing, "
    "nonzero/unique, traced slice bounds) — untraceable or recompiles "
    "per value",
)
def check_nonstatic_shape(ctx: FileContext) -> Iterator[Hit]:
    taint_cache: dict[FuncNode, set[str]] = {}

    def traced_for(node: ast.AST) -> set[str]:
        fn = ctx.enclosing_function(node)
        if fn is None:
            return set()
        if fn not in taint_cache:
            taint_cache[fn] = ctx.traced_names(fn)
        return taint_cache[fn]

    for node in ast.walk(ctx.tree):
        if not ctx.in_jit_context(node):
            continue
        if isinstance(node, ast.Subscript):
            if _is_boolean_mask(node.slice):
                yield (
                    node,
                    "boolean-mask indexing inside jit — the result shape "
                    "depends on data; use jnp.where(mask, x, fill) or a "
                    "fixed-size jnp.nonzero(..., size=...)",
                )
            elif isinstance(node.slice, ast.Slice):
                traced = traced_for(node)
                bounds = [
                    b
                    for b in (node.slice.lower, node.slice.upper, node.slice.step)
                    if b is not None
                ]
                if traced and any(ctx.expr_is_traced(b, traced) for b in bounds):
                    yield (
                        node,
                        "slice bound is a traced value inside jit — the "
                        "shape becomes data-dependent; use "
                        "lax.dynamic_slice with a static size or mask "
                        "instead of slicing",
                    )
        elif isinstance(node, ast.Call):
            cname = call_name(node)
            if cname is None:
                continue
            leaf = cname.rsplit(".", 1)[-1]
            if leaf in _DATA_DEPENDENT_CALLS and cname.startswith(
                ("jnp.", "jax.numpy.", "np.", "numpy.")
            ):
                if not any(kw.arg == "size" for kw in node.keywords):
                    yield (
                        node,
                        f"{leaf}() inside jit has a data-dependent output "
                        "shape — pass size= (with fill_value) or "
                        "restructure to a masked fixed-shape computation",
                    )
            elif leaf == "where" and cname.startswith(("jnp.", "jax.numpy.")):
                if len(node.args) + len(node.keywords) == 1:
                    yield (
                        node,
                        "single-argument jnp.where inside jit returns "
                        "data-dependent-length indices — use the "
                        "three-argument form or nonzero(size=...)",
                    )


# --------------------------------------------------------------------------
# 5. dce-timed-region
# --------------------------------------------------------------------------

_TIME_CALLS = frozenset(
    {"time.perf_counter", "time.time", "time.monotonic", "perf_counter"}
)
_TIMER_NAMES = frozenset({"Timer", "timed"})
_REGION_SYNC_OK = frozenset({"float", "int"})  # float(...) of a result fences


def _is_time_call(expr: ast.AST) -> bool:
    return isinstance(expr, ast.Call) and call_name(expr) in _TIME_CALLS


def _region_has_sync(stmts: list[ast.stmt], ctx: FileContext) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                if _sync_kind(node, ctx, None) is not None:
                    return True
                cname = call_name(node)
                if cname in _REGION_SYNC_OK and node.args:
                    return True
    return False


def _names_loaded(nodes: Iterator[ast.AST] | list[ast.stmt]) -> set[str]:
    out: set[str] = set()
    seq = nodes if isinstance(nodes, list) else list(nodes)
    for n in seq:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
    return out


def _audit_timed_region(
    region: list[ast.stmt],
    after: list[ast.stmt],
    ctx: FileContext,
) -> Iterator[Hit]:
    """Flag a timed region whose computed results are never consumed —
    XLA (async dispatch + DCE) then times nothing."""
    if _region_has_sync(region, ctx):
        return
    used_later = _names_loaded(after)
    for stmt in region:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            if _is_device_dispatch(stmt.value, ctx):
                yield (
                    stmt,
                    "timed region discards a device call's result with no "
                    "block_until_ready/host fetch — async dispatch + XLA "
                    "DCE make the measurement meaningless",
                )
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if not _is_device_dispatch(stmt.value, ctx):
                continue
            targets = {
                t.id for t in stmt.targets if isinstance(t, ast.Name)
            }
            if targets and not (targets & used_later):
                yield (
                    stmt,
                    "timed region computes a device value that is never "
                    "read afterwards and never fenced — XLA dead-code-"
                    "eliminates the measured work",
                )


@rule(
    "dce-timed-region",
    "timed region whose device results are unconsumed/unfenced, or a "
    "measurement loop body consuming only one element of its result — XLA "
    "DCEs the measured work (the tools/xla_cost_micro bug class)",
)
def check_dce_timed(ctx: FileContext) -> Iterator[Hit]:
    # (a) host-level: t0 = perf_counter() ... perf_counter() - t0 regions
    for parent in ast.walk(ctx.tree):
        body_lists = [
            getattr(parent, field)
            for field in ("body", "orelse", "finalbody")
            if isinstance(getattr(parent, field, None), list)
        ]
        for stmts in body_lists:
            for i, stmt in enumerate(stmts):
                if not (
                    isinstance(stmt, ast.Assign)
                    and _is_time_call(stmt.value)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    continue
                t_name = stmt.targets[0].id
                end = None
                for j in range(i + 1, len(stmts)):
                    for sub in ast.walk(stmts[j]):
                        if (
                            isinstance(sub, ast.BinOp)
                            and isinstance(sub.op, ast.Sub)
                            and _is_time_call(sub.left)
                            and isinstance(sub.right, ast.Name)
                            and sub.right.id == t_name
                        ):
                            end = j
                            break
                    if end is not None:
                        break
                if end is None or end == i + 1:
                    continue
                region, after = stmts[i + 1 : end], stmts[end:]
                yield from _audit_timed_region(region, after, ctx)

            # with Timer() as t: blocks
            for stmt in stmts:
                if not isinstance(stmt, (ast.With, ast.AsyncWith)):
                    continue
                timer_like = any(
                    isinstance(item.context_expr, ast.Call)
                    and (call_name(item.context_expr) or "").rsplit(".", 1)[-1]
                    in _TIMER_NAMES
                    for item in stmt.items
                )
                if not timer_like:
                    continue
                idx = stmts.index(stmt)
                yield from _audit_timed_region(stmt.body, stmts[idx + 1 :], ctx)

    # (b) device-level: inside a lax loop body, a computed result consumed
    # only through a constant single-element subscript (the "out.ravel()[0]"
    # chaining bug — everything but element 0 is dead and DCEd).
    for fn in ctx.lax_bodies:
        body = fn.body if isinstance(fn.body, list) else []
        for stmt in body if isinstance(body, list) else []:
            if not (
                isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                continue
            name = stmt.targets[0].id
            uses = [
                n
                for n in _walk_own_body(fn)
                if isinstance(n, ast.Name)
                and n.id == name
                and isinstance(n.ctx, ast.Load)
            ]
            if uses and all(_use_is_single_element(u, ctx) for u in uses):
                yield (
                    stmt,
                    f"measurement loop consumes only one element of "
                    f"`{name}` — XLA dead-code-eliminates the rest of the "
                    "measured work; reduce over the whole result (e.g. "
                    "jnp.abs(x).min()) to keep it live",
                )


# --------------------------------------------------------------------------
# 6. unguarded-host-sync
# --------------------------------------------------------------------------

# Directory components whose host syncs must route through the resilience
# executor (retry/backoff, sync deadlines, the CPU degradation ladder, and
# ResilienceExhausted-with-checkpoint).  resilience/ itself is exempt — it
# is where the raw calls legitimately live.
_GUARDED_TREE_DIRS = frozenset(
    {"models", "parallel", "io", "serving", "dataflow"}
)
_RAW_SYNC_CALLS = frozenset({"jax.device_get", "jax.block_until_ready"})
_ASARRAY_CALLS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
)
_DISPATCH_ROOTS = ("jnp.", "jax.", "lax.")


def _device_bound_names(fn: FuncNode | None, ctx: FileContext) -> set[str]:
    """Names assigned from an expression containing a jnp/jax/lax dispatch
    call, within ``fn``'s own body (module scope when fn is None) — the
    light taint that makes ``np.asarray(ranks_dev)`` detectable."""
    scope: list[ast.stmt]
    if fn is None:
        scope = ctx.tree.body
    else:
        scope = fn.body if isinstance(fn.body, list) else []
    out: set[str] = set()
    for stmt in scope:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            if not _contains_device_call(node.value):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    out.update(
                        e.id for e in tgt.elts if isinstance(e, ast.Name)
                    )
    return out


@rule(
    "unguarded-host-sync",
    "raw jax.device_get / .block_until_ready() / np.asarray(device value) "
    "in models/, parallel/, io/, serving/ or dataflow/ — host syncs there must route "
    "through "
    "resilience.executor so retries, sync deadlines and the degradation "
    "ladder apply (ratchet stays at zero: migrate, don't baseline)",
)
def check_unguarded_sync(ctx: FileContext) -> Iterator[Hit]:
    parts = ctx.relpath.split("/")
    if not (set(parts[:-1]) & _GUARDED_TREE_DIRS):
        return
    taint_cache: dict[FuncNode | None, set[str]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname in _RAW_SYNC_CALLS:
            yield (
                node,
                f"raw {cname} outside the resilience executor — use "
                "resilience.executor.device_get / .block_until_ready (or "
                "run_guarded) so retry, sync-deadline and degradation apply",
            )
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"
            and not node.args
        ):
            yield (
                node,
                "raw .block_until_ready() outside the resilience executor "
                "— use resilience.executor.block_until_ready so a hung "
                "fence hits the sync deadline instead of wedging the run",
            )
            continue
        if cname in _ASARRAY_CALLS and len(node.args) == 1:
            arg = node.args[0]
            devicey = _contains_device_call(arg)
            if not devicey and isinstance(arg, ast.Name):
                fn = ctx.enclosing_function(node)
                if fn not in taint_cache:
                    taint_cache[fn] = _device_bound_names(fn, ctx)
                devicey = arg.id in taint_cache[fn]
            if devicey:
                yield (
                    node,
                    f"{cname} of a device value is a hidden host sync — "
                    "pull through resilience.executor.device_get so retry, "
                    "sync-deadline and degradation apply",
                )


# --------------------------------------------------------------------------
# 7. untraced-guarded-site
# --------------------------------------------------------------------------

# Guarded-executor entry points whose call sites must sit inside an active
# span: the resilience ladder's retry/watchdog/degrade events are only
# attributable when the trace records WHICH phase the guarded call served
# (the round-5 lesson: a 420 s TF-IDF death at chunk 24 left no accounting).
# Matched as a bare name or under the conventional executor aliases; an
# explicit jax./np. prefix is the RAW call — unguarded-host-sync territory.
_GUARDED_LEAVES = frozenset({"device_get", "block_until_ready"})
_GUARDED_ROOTS = frozenset({"", "rx", "executor", "resilience.executor"})
# with-items that open a span: obs.span(...) / span(...) and the
# profiling.annotate(...) alias (which delegates to obs.span).
_SPAN_LEAVES = frozenset({"span", "annotate"})


def _inside_span(node: ast.AST, ctx: FileContext) -> bool:
    """Is ``node`` lexically inside a ``with obs.span(...)``-style block in
    its own function?  A caller's span is not lexically visible (same
    convention as ``_under_lock``): functions whose bodies run guarded
    calls open their own span."""
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    cname = call_name(expr)
                    if cname and cname.rsplit(".", 1)[-1] in _SPAN_LEAVES:
                        return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        cur = ctx.parents.get(cur)
    return False


@rule(
    "untraced-guarded-site",
    "run_guarded / guarded device_get / block_until_ready call site in "
    "models/, parallel/, io/, serving/ or dataflow/ outside an active obs.span — the "
    "resilience "
    "ladder's retry/watchdog/degrade events would land in the trace with "
    "no phase to attribute them to",
)
def check_untraced_guarded_site(ctx: FileContext) -> Iterator[Hit]:
    parts = ctx.relpath.split("/")
    if not (set(parts[:-1]) & _GUARDED_TREE_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname is None:
            continue
        leaf = cname.rsplit(".", 1)[-1]
        root = cname[: -len(leaf) - 1] if "." in cname else ""
        guarded = leaf == "run_guarded" or (
            leaf in _GUARDED_LEAVES and root in _GUARDED_ROOTS
        )
        if not guarded:
            continue
        if _inside_span(node, ctx):
            continue
        yield (
            node,
            f"guarded call {cname} outside an active span — wrap the "
            "region in `with obs.span(\"<phase>\", ...)` so the trace can "
            "attribute the executor's retry/watchdog/degrade events (and "
            "the wall time) to a phase",
        )


# --------------------------------------------------------------------------
# 8. unsynced-thread-state
# --------------------------------------------------------------------------

# Methods that mutate their receiver in place.
_MUTATOR_METHODS = frozenset(
    {
        "append", "extend", "insert", "add", "update", "setdefault",
        "pop", "popitem", "remove", "discard", "clear",
    }
)


def _stmt_target_names(tgt: ast.expr) -> Iterator[str]:
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for e in tgt.elts:
            yield from _stmt_target_names(e)
    elif isinstance(tgt, ast.Starred):
        yield from _stmt_target_names(tgt.value)


def _module_level_names(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                out.update(_stmt_target_names(t))
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
    return out


def _is_lockish(expr: ast.AST) -> bool:
    """``with <expr>:`` counts as a critical section when the context
    expression's dotted spelling mentions a lock (``self._lock``,
    ``_LOCK``, ``lock.acquire()``, ``threading.RLock()`` ...)."""
    name = dotted_name(expr)
    if name is None and isinstance(expr, ast.Call):
        name = call_name(expr)
    return name is not None and "lock" in name.lower()


def _under_lock(node: ast.AST, ctx: FileContext) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)) and any(
            _is_lockish(item.context_expr) for item in cur.items
        ):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False  # a caller's lock is not lexically visible
        cur = ctx.parents.get(cur)
    return False


def _thread_targets(ctx: FileContext) -> set[FuncNode]:
    """Functions handed to ``threading.Thread(target=...)``, plus same-file
    functions they call *outside* a lock (the body effectively runs on the
    spawned thread too)."""
    defs_by_name: dict[str, list[FuncNode]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    targets: set[FuncNode] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        if cname not in ("threading.Thread", "Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Lambda):
                targets.add(v)
            elif isinstance(v, ast.Name):
                targets.update(defs_by_name.get(v.id, []))
            elif isinstance(v, ast.Attribute):  # target=self._run
                targets.update(defs_by_name.get(v.attr, []))

    changed = True
    while changed:
        changed = False
        for fn in list(targets):
            for node in _walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                if cname is None or "." in cname or _under_lock(node, ctx):
                    continue
                for callee in defs_by_name.get(cname, []):
                    if callee not in targets:
                        targets.add(callee)
                        changed = True
    return targets


@rule(
    "unsynced-thread-state",
    "module-level or instance state mutated inside a threading.Thread "
    "target without holding a lock — a data race against the spawning "
    "thread (the watchdog/prefetch bug class)",
)
def check_unsynced_thread_state(ctx: FileContext) -> Iterator[Hit]:
    targets = _thread_targets(ctx)
    if not targets:
        return
    module_names = _module_level_names(ctx.tree)

    for fn in targets:
        global_names: set[str] = set()
        for node in _walk_own_body(fn):
            if isinstance(node, ast.Global):
                global_names.update(node.names)

        for node in _walk_own_body(fn):
            shared: str | None = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id in global_names:
                        shared = f"module global `{t.id}`"
                    elif isinstance(t, (ast.Attribute, ast.Subscript)):
                        base = dotted_name(t.value)
                        root_name = (base or "").split(".")[0]
                        if root_name == "self":
                            shared = f"instance state `{base}...`"
                        elif root_name in module_names or root_name in global_names:
                            shared = f"module-level `{base}`"
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS
            ):
                base = dotted_name(node.func.value)
                root_name = (base or "").split(".")[0]
                if root_name == "self":
                    shared = f"instance state `{base}.{node.func.attr}(...)`"
                elif root_name in module_names:
                    shared = f"module-level `{base}.{node.func.attr}(...)`"
            if shared is None or _under_lock(node, ctx):
                continue
            yield (
                node,
                f"thread-target function mutates {shared} without holding "
                "a lock — the spawning thread (or another worker) can race "
                "this write; guard it with `with <lock>:` or confine the "
                "state to one thread",
            )


# --------------------------------------------------------------------------
# 8b. thread-registry-drift
# --------------------------------------------------------------------------

# The declared thread inventory (utils/config.py THREAD_REGISTRY): rows of
# (name glob, owning module, locks it may hold).  This rule is the
# name-side validation companion of ``unsynced-thread-state`` — the same
# Thread-construction surface, checked against the declaration both
# directions; the locks-held direction lives in the tier-4 concurrency
# analyzer (``thread-lock-drift``), which shares these helpers.

_thread_registry_cache: dict = {}


def _parse_declared_rows(cfg_path, name: str) -> "tuple | None":
    """Lexically extract a tuple-of-tuples literal assigned to ``name``:
    each row becomes a tuple whose string elements are kept as-is and
    whose nested tuple/list elements become tuples of their string
    constants.  None when the file has no declaration."""
    try:
        tree = ast.parse(cfg_path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        if not isinstance(value, (ast.Tuple, ast.List)):
            return None
        rows = []
        for row in value.elts:
            if not isinstance(row, (ast.Tuple, ast.List)):
                continue
            fields: list = []
            for elt in row.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    fields.append(elt.value)
                elif isinstance(elt, (ast.Tuple, ast.List)):
                    fields.append(tuple(
                        e.value for e in elt.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ))
            rows.append(tuple(fields))
        return tuple(rows)
    return None


def thread_registry_rows(root) -> "tuple | None":
    """THREAD_REGISTRY rows for the scanned tree (falling back to this
    package's own utils/config.py for bare snippet lints); cached per
    root.  Each row is ``(name_glob, module, locks)``."""
    from pathlib import Path

    key = str(root) if root is not None else ""
    if key in _thread_registry_cache:
        return _thread_registry_cache[key]
    candidates = []
    if root is not None:
        candidates += [
            Path(root) / "page_rank_and_tfidf_using_apache_spark_tpu/utils/config.py",
            Path(root) / "utils/config.py",
        ]
    candidates.append(Path(__file__).resolve().parents[1] / "utils" / "config.py")
    rows = None
    for c in candidates:
        if c.exists():
            rows = _parse_declared_rows(c, "THREAD_REGISTRY")
            if rows is not None:
                break
    _thread_registry_cache[key] = rows
    return rows


def resolve_thread_name(ctx: FileContext, expr: ast.AST | None,
                        node: ast.AST) -> str | None:
    """Static thread-name resolution: a string literal resolves to itself,
    an f-string to a glob (formatted fields become ``*``), and a bare name
    to the enclosing function parameter's string default.  None = the name
    is not statically resolvable (or absent)."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        glob = "".join(parts)
        return glob if glob.strip("*") else None
    if isinstance(expr, ast.Name):
        fn = ctx.enclosing_function(node)
        if fn is None or isinstance(fn, ast.Lambda):
            return None
        a = fn.args
        params = a.posonlyargs + a.args
        for p, d in zip(params[len(params) - len(a.defaults):], a.defaults):
            if p.arg == expr.id and isinstance(d, ast.Constant) \
                    and isinstance(d.value, str):
                return d.value
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == expr.id and isinstance(d, ast.Constant) \
                    and isinstance(d.value, str):
                return d.value
    return None


def _names_match(resolved: str, declared: str) -> bool:
    import fnmatch

    return resolved == declared or fnmatch.fnmatch(resolved, declared)


@rule(
    "thread-registry-drift",
    "threading.Thread constructed with a name not declared in "
    "utils/config.py THREAD_REGISTRY (or with no statically-resolvable "
    "name at all), or a declared thread no code implements — the one-"
    "process runtime's thread inventory must stay a checked declaration, "
    "not reviewer folklore",
)
def check_thread_registry_drift(ctx: FileContext) -> Iterator[Hit]:
    rows = thread_registry_rows(ctx.root)
    if ctx.relpath.endswith("utils/config.py"):
        # declaration side (the ladder-rung-drift convention): every
        # declared thread must be implemented — its name's literal prefix
        # must appear in the declared module's source.
        if rows is None or ctx.root is None:
            return
        for row in rows:
            if len(row) < 2:
                continue
            name, module = row[0], row[1]
            path = ctx.root / module
            prefix = name.split("*", 1)[0]
            try:
                implemented = path.exists() and (
                    not prefix or prefix in path.read_text(encoding="utf-8")
                )
            except OSError:
                implemented = False
            if not implemented:
                yield (
                    ctx.tree,
                    f"declared thread {name!r} is implemented nowhere in "
                    f"{module} — construct the thread there (literal "
                    "name) or drop the THREAD_REGISTRY row",
                )
        return

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in ("threading.Thread", "Thread"):
            continue
        name_expr = next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None
        )
        if name_expr is None:
            yield (
                node,
                "threading.Thread constructed without a name= — give the "
                "thread a literal name and declare it in utils/config.py "
                "THREAD_REGISTRY (name, owning module, locks it may hold)",
            )
            continue
        resolved = resolve_thread_name(ctx, name_expr, node)
        if resolved is None:
            yield (
                node,
                "thread name is not statically resolvable — use a string "
                "literal or f-string name so THREAD_REGISTRY can be "
                "validated against it",
            )
            continue
        if rows is None:
            yield (
                node,
                f"thread {resolved!r} but no THREAD_REGISTRY declaration "
                "found — declare the thread inventory in utils/config.py",
            )
            continue
        matched = [r for r in rows if len(r) >= 2 and _names_match(resolved, r[0])]
        if not matched:
            yield (
                node,
                f"thread {resolved!r} is not declared in utils/config.py "
                "THREAD_REGISTRY — register (name, owning module, locks it "
                "may hold) before spawning it",
            )
        elif not any(r[1] == ctx.relpath for r in matched):
            yield (
                node,
                f"thread {resolved!r} is declared for module "
                f"{matched[0][1]!r} but constructed in {ctx.relpath!r} — "
                "move the construction or fix the THREAD_REGISTRY row",
            )


# --------------------------------------------------------------------------
# 9. env-knob-drift
# --------------------------------------------------------------------------

_knob_cache: dict[str, frozenset | None] = {}


def _parse_declared_literal(cfg_path, name: str) -> frozenset | None:
    """Lexically extract a string-literal collection assigned to ``name``
    in a config module (never imports it — the linter must run even when
    the package is broken).  None when the file has no declaration."""
    try:
        tree = ast.parse(cfg_path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if any(isinstance(t, ast.Name) and t.id == name for t in targets):
            return frozenset(
                n.value
                for n in ast.walk(value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            )
    return None


def _declared_config_literal(
    ctx: FileContext, name: str, cache: dict
) -> frozenset | None:
    """Resolve ``name``'s declaration from the scanned tree's utils/
    config.py, falling back to this package's own (snippet lints); cached
    per lint root."""
    from pathlib import Path

    key = str(ctx.root) if ctx.root is not None else ""
    if key in cache:
        return cache[key]
    candidates = []
    if ctx.root is not None:
        candidates += [
            ctx.root / "page_rank_and_tfidf_using_apache_spark_tpu/utils/config.py",
            ctx.root / "utils/config.py",
        ]
    candidates.append(Path(__file__).resolve().parents[1] / "utils" / "config.py")
    declared = None
    for c in candidates:
        if c.exists():
            declared = _parse_declared_literal(c, name)
            if declared is not None:
                break
    cache[key] = declared
    return declared


def _declared_knobs(ctx: FileContext) -> frozenset | None:
    return _declared_config_literal(ctx, "GRAFT_ENV_KNOBS", _knob_cache)


@rule(
    "env-knob-drift",
    "os.environ read of a GRAFT_* knob that is not declared in "
    "utils/config.py GRAFT_ENV_KNOBS — knobs must be registered (and "
    "documented) before code may read them",
)
def check_env_knob_drift(ctx: FileContext) -> Iterator[Hit]:
    if ctx.relpath.endswith("utils/config.py"):
        return  # the declaration site itself

    reads: list[tuple[ast.AST, str]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname in ("os.environ.get", "os.getenv", "environ.get") and node.args:
                a = node.args[0]
                if (
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and a.value.startswith("GRAFT_")
                ):
                    reads.append((node, a.value))
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            base = dotted_name(node.value)
            if base in ("os.environ", "environ"):
                s = node.slice
                if (
                    isinstance(s, ast.Constant)
                    and isinstance(s.value, str)
                    and s.value.startswith("GRAFT_")
                ):
                    reads.append((node, s.value))
    if not reads:
        return
    knobs = _declared_knobs(ctx)
    for node, knob in reads:
        if knobs is not None and knob in knobs:
            continue
        where = (
            "no GRAFT_ENV_KNOBS declaration found"
            if knobs is None
            else "not in utils/config.py GRAFT_ENV_KNOBS"
        )
        yield (
            node,
            f"undeclared env knob {knob!r} ({where}) — declare it in "
            "GRAFT_ENV_KNOBS with a comment and document it in the README "
            "env-knob table before reading it",
        )


# --------------------------------------------------------------------------
# 9b. metric-name-drift
# --------------------------------------------------------------------------

# The declared metric-name contract (analysis/registry.py METRIC_SCHEMAS):
# rows of (name glob, kind, unit, publishing sites).  Two namespaces share
# it — run-aggregate publishes (``obs.counter/gauge/histogram``) and live-
# SLO hub publishes (``hub.count/counter/gauge``, plus MetricsHub's own
# ``self.*`` calls) — because both end up in operator-facing surfaces
# (run summary / trace_report on one side, /metrics / slo_watch /
# federation on the other) where a silent rename breaks every reader.

_metric_schema_cache: dict = {}

_METRIC_KINDS = frozenset({"counter", "gauge", "histogram", "slo"})
_METRIC_CALL_KIND = {
    "count": "counter",
    "counter": "counter",
    "gauge": "gauge",
    "histogram": "histogram",
}
_METRIC_RECEIVERS = frozenset({"obs", "hub"})


def _parse_metric_rows(reg_path) -> "tuple | None":
    """Lexically extract METRIC_SCHEMAS rows from analysis/registry.py,
    resolving the registry's ``f"{_PKG}/..."`` site paths through its
    module-level string constants (never imports — the linter must run
    even when the package is broken).  None when the file has no
    declaration."""
    try:
        tree = ast.parse(reg_path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    consts: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value

    def lit(elt: ast.AST) -> str | None:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
            return elt.value
        if isinstance(elt, ast.JoinedStr):
            parts = []
            for v in elt.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    parts.append(v.value)
                elif isinstance(v, ast.FormattedValue) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id in consts:
                    parts.append(consts[v.value.id])
                else:
                    return None
            return "".join(parts)
        return None

    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "METRIC_SCHEMAS"
                   for t in targets):
            continue
        if not isinstance(value, (ast.Tuple, ast.List)):
            return None
        rows = []
        for row in value.elts:
            if not isinstance(row, (ast.Tuple, ast.List)) or \
                    len(row.elts) != 4:
                continue
            name, kind, unit = (lit(e) for e in row.elts[:3])
            sites_elt = row.elts[3]
            sites = tuple(
                s for s in (lit(e) for e in sites_elt.elts) if s
            ) if isinstance(sites_elt, (ast.Tuple, ast.List)) else ()
            if name and kind:
                rows.append((name, kind, unit or "", sites))
        return tuple(rows)
    return None


def metric_schema_rows(root) -> "tuple | None":
    """METRIC_SCHEMAS rows for the scanned tree (falling back to this
    package's own analysis/registry.py for bare snippet lints); cached
    per root.  Each row is ``(name_glob, kind, unit, sites)``."""
    from pathlib import Path

    key = str(root) if root is not None else ""
    if key in _metric_schema_cache:
        return _metric_schema_cache[key]
    candidates = []
    if root is not None:
        candidates += [
            Path(root) / "page_rank_and_tfidf_using_apache_spark_tpu/analysis/registry.py",
            Path(root) / "analysis/registry.py",
        ]
    candidates.append(Path(__file__).resolve().parent / "registry.py")
    rows = None
    for c in candidates:
        if c.exists():
            rows = _parse_metric_rows(c)
            if rows is not None:
                break
    _metric_schema_cache[key] = rows
    return rows


@rule(
    "metric-name-drift",
    "a metric published under a name not declared in analysis/registry.py "
    "METRIC_SCHEMAS (or from a module the row does not list, or with a "
    "kind the row contradicts), or a declared metric no site publishes — "
    "every dashboard, slo_watch board, trace_diff gate and federation "
    "merge keys on these names, so the name space is a checked contract",
)
def check_metric_name_drift(ctx: FileContext) -> Iterator[Hit]:
    rows = metric_schema_rows(ctx.root)
    if ctx.relpath.endswith("analysis/registry.py"):
        # declaration side: every row's kind must be known and its name's
        # literal fragments must appear in every site it claims (glob
        # names check their non-* fragments, the f-string publish pattern)
        if rows is None or ctx.root is None:
            return
        for name, kind, _unit, sites in rows:
            if kind not in _METRIC_KINDS:
                yield (
                    ctx.tree,
                    f"METRIC_SCHEMAS row {name!r} declares unknown kind "
                    f"{kind!r} (expected one of {sorted(_METRIC_KINDS)})",
                )
            frags = [f for f in name.split("*") if f]
            for site in sites:
                path = ctx.root / site
                try:
                    text = path.read_text(encoding="utf-8") \
                        if path.exists() else None
                except OSError:
                    text = None
                if text is None or not all(f in text for f in frags):
                    yield (
                        ctx.tree,
                        f"METRIC_SCHEMAS declares {name!r} published from "
                        f"{site} but the name appears nowhere there — "
                        "stale registry row or renamed metric",
                    )
        return

    # usage side: every literal-named publish call must be covered by a
    # row — name, kind and publishing module.  Variable names (e.g.
    # ingest_event's `self.count(kind)` passthrough) are skipped; their
    # kind-set literals are validated by the declaration side above.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)):
            continue
        kind = _METRIC_CALL_KIND.get(fn.attr)
        if kind is None:
            continue
        recv = fn.value.id
        if recv not in _METRIC_RECEIVERS and not (
                recv == "self" and ctx.relpath.endswith("obs/metrics.py")):
            continue
        resolved = resolve_thread_name(ctx, node.args[0], node)
        if resolved is None:
            continue
        if rows is None:
            yield (
                node,
                f"metric {resolved!r} published but no METRIC_SCHEMAS "
                "declaration found — declare the metric-name contract in "
                "analysis/registry.py",
            )
            continue
        matched = [r for r in rows if _names_match(resolved, r[0])]
        if not matched:
            yield (
                node,
                f"metric {resolved!r} is not declared in "
                "analysis/registry.py METRIC_SCHEMAS — register (name, "
                "kind, unit, publishing sites) before publishing it",
            )
            continue
        kinded = [r for r in matched if r[1] == kind]
        if not kinded:
            yield (
                node,
                f"metric {resolved!r} is published as a {kind} but "
                f"METRIC_SCHEMAS declares it {matched[0][1]!r} — a kind "
                "change breaks every reader's aggregation; fix one side",
            )
        elif not any(ctx.relpath == s for r in kinded for s in r[3]):
            yield (
                node,
                f"metric {resolved!r} is published from {ctx.relpath!r} "
                "which its METRIC_SCHEMAS row does not list — add the "
                "site or move the publish",
            )


# --------------------------------------------------------------------------
# 10. ladder-rung-drift
# --------------------------------------------------------------------------

_ladder_cache: dict[str, frozenset | None] = {}


def _declared_ladder(ctx: FileContext) -> frozenset | None:
    return _declared_config_literal(ctx, "DEGRADE_LADDER", _ladder_cache)


def _degraded_ladder_kwargs(tree: ast.AST) -> Iterator[tuple[ast.AST, str]]:
    """Every string-literal ``ladder=`` kwarg on an
    ``emit("degraded", ...)`` / ``record(event="degraded", ...)`` call."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        is_degraded = any(
            isinstance(a, ast.Constant) and a.value == "degraded"
            for a in node.args
        ) or any(
            kw.arg == "event"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value == "degraded"
            for kw in node.keywords
        )
        if not is_degraded:
            continue
        for kw in node.keywords:
            if (
                kw.arg == "ladder"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ):
                yield node, kw.value.value


@rule(
    "ladder-rung-drift",
    "degradation-ladder drift against utils/config.py DEGRADE_LADDER: a "
    "`degraded` event emitted with an undeclared ladder name, or a "
    "declared rung no resilience/ module implements — the ladder the docs "
    "promise and the ladder the code walks must be the same ladder",
)
def check_ladder_rung_drift(ctx: FileContext) -> Iterator[Hit]:
    ladder = _declared_ladder(ctx)
    if ctx.relpath.endswith("utils/config.py"):
        # declaration side: every declared rung must be implemented — i.e.
        # appear as a string literal somewhere under resilience/ (the
        # subsystem that owns degradation).  Checked from the declaration
        # site so the finding lands where the fix (or the deletion) goes.
        if ladder is None or ctx.root is None:
            return
        res_dirs = [
            ctx.root / "page_rank_and_tfidf_using_apache_spark_tpu/resilience",
            ctx.root / "resilience",
        ]
        files = [p for d in res_dirs if d.is_dir() for p in d.glob("*.py")]
        if not files:
            return  # nothing to check against (snippet trees)
        seen: set[str] = set()
        for p in files:
            try:
                t = ast.parse(p.read_text(encoding="utf-8"))
            except (OSError, SyntaxError):
                continue
            seen.update(
                n.value
                for n in ast.walk(t)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            )
        for rung in sorted(ladder - seen):
            yield (
                ctx.tree,
                f"declared rung {rung!r} is referenced nowhere under "
                "resilience/ — implement the rung (it must publish a "
                "`degraded` event) or drop it from DEGRADE_LADDER",
            )
        return

    for node, name in _degraded_ladder_kwargs(ctx.tree):
        if ladder is not None and name in ladder:
            continue
        where = (
            "no DEGRADE_LADDER declaration found"
            if ladder is None
            else "not in utils/config.py DEGRADE_LADDER"
        )
        yield (
            node,
            f"`degraded` event emitted with undeclared ladder {name!r} "
            f"({where}) — declare the rung in DEGRADE_LADDER (and the "
            "README ladder table) before code may take it",
        )


def _use_is_single_element(use: ast.Name, ctx: FileContext) -> bool:
    """True if this load feeds only a constant element access like
    ``x[0]``, ``x[0, 0]`` or ``x.ravel()[0]``."""
    node: ast.AST = use
    parent = ctx.parents.get(node)
    # allow a .ravel()/.flatten()/.reshape() hop
    if (
        isinstance(parent, ast.Attribute)
        and parent.attr in ("ravel", "flatten", "reshape")
    ):
        grand = ctx.parents.get(parent)
        if isinstance(grand, ast.Call):
            node, parent = grand, ctx.parents.get(grand)
    if isinstance(parent, ast.Subscript) and parent.value is node:
        idx = parent.slice
        if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
            return True
        if isinstance(idx, ast.Tuple) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in idx.elts
        ):
            return True
    return False


# --------------------------------------------------------------------------
# 12. sync-put-in-ingest-loop
# --------------------------------------------------------------------------

# Directory components whose per-chunk H2D transfers must route through the
# staging API (dataflow.ingest.staged_put / the chunked_ingest stage
# closure): a raw jax.device_put inside an ingest loop body serializes the
# pipeline — the transfer blocks the thread that should be dispatching
# chunk N while chunk N+1 transfers — and sits outside the
# ``ingest_h2d_put`` chaos/retry site, so device loss during the put
# bypasses the pipeline's recovery point.
_INGEST_PUT_DIRS = frozenset({"dataflow", "models", "parallel"})
_STAGED_PUT_LEAF = "staged_put"


def _under_staged_put(node: ast.AST, ctx: FileContext) -> bool:
    """Is ``node`` lexically inside an argument of a ``staged_put(...)``
    call (any alias path: ``staged_put`` / ``dflow.staged_put`` /
    ``ingest.staged_put``)?  The conventional shape is a lambda/closure
    handed to staged_put, whose body issues the raw puts."""
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call):
            cname = call_name(cur)
            if cname and cname.rsplit(".", 1)[-1] == _STAGED_PUT_LEAF:
                return True
        cur = ctx.parents.get(cur)
    return False


@rule(
    "sync-put-in-ingest-loop",
    "raw jax.device_put inside a loop body in dataflow/, models/ or "
    "parallel/ outside the staging API (dataflow.ingest.staged_put) — "
    "per-chunk H2D transfers must run on the pipeline's staging stage so "
    "they overlap compute, retry transients, and surface device loss at "
    "the pipeline's recovery point (ratchet stays at zero: migrate, "
    "don't baseline)",
)
def check_sync_put_in_ingest_loop(ctx: FileContext) -> Iterator[Hit]:
    parts = ctx.relpath.split("/")
    if not (set(parts[:-1]) & _INGEST_PUT_DIRS):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) != "jax.device_put":
            continue
        if not ctx.enclosing_loops(node):
            continue
        if _under_staged_put(node, ctx):
            continue
        yield (
            node,
            "raw jax.device_put inside a loop body — route the transfer "
            "through dataflow.ingest.staged_put (or the chunked_ingest "
            "stage closure) so it runs on the staging stage: overlapped "
            "with compute, retried on transients, and recoverable at the "
            "pipeline's recovery point on device loss",
        )
