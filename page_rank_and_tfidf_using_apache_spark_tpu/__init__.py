"""TPU-native PageRank + TF-IDF framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
``ajak6/Page-Rank-and-TFIDF-using-Apache-Spark`` (a Spark-RDD application;
see SURVEY.md — the reference checkout was empty at survey time, so parity
targets are reconstructed from the driver metadata in BASELINE.json and the
canonical Spark PageRank / TF-IDF programs it fingerprints).

Where the reference expresses PageRank as
``links.join(ranks).flatMap(computeContribs).reduceByKey(add)`` shuffles over
RDD partitions (BASELINE.json:5), this framework keeps the graph
device-resident as sorted edge arrays and runs each iteration as one
XLA-compiled sparse matvec: ``segment_sum`` for the intra-chip combine,
``lax.psum`` over ICI for the cross-chip combine and dangling mass.  Where
the reference's TF-IDF is ``flatMap(tokenize) → reduceByKey`` term-count and
document-frequency passes, this framework hashes tokens on host into a
``2**v`` vocabulary and runs both passes as ``segment_sum`` over device
arrays, with the IDF vector broadcast (replicated) across chips.

Layout (mirrors SURVEY.md §7's build plan):

- ``io/``        host-side ingest: SNAP edge lists → CSR/edge arrays,
                 corpus loading, tokenization, hashed vocabulary
- ``ops/``       jittable numeric cores: SpMV-based PageRank step,
                 segment-sum TF/DF passes, IDF variants
- ``models/``    user-facing algorithm drivers: PageRank (standard,
                 personalized, spark-semantics), TF-IDF (batch, streaming)
- ``parallel/``  mesh construction, shardings, collectives, multi-host init
- ``utils/``     configs, metrics, checkpointing, profiling, native bindings
- ``cli/``       argparse drivers mirroring the reference's
                 ``spark-submit <script> <input> <iters> [output]`` shape
"""

from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    PageRankConfig,
    TfidfConfig,
)
from page_rank_and_tfidf_using_apache_spark_tpu.api import pagerank, tfidf
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import (
    ResilienceExhausted,
)

__version__ = "0.1.0"

__all__ = [
    "PageRankConfig",
    "ResilienceExhausted",
    "TfidfConfig",
    "pagerank",
    "tfidf",
    "__version__",
]
