"""Lucene-style delta segments over the versioned array-dir artifact
(ISSUE 13): the ingest→servable path in seconds, not a full rebuild.

A *segmented index directory* holds immutable segment artifacts plus a
versioned manifest naming the live set::

    index_dir/
      LATEST                 -> "manifest_000007.json"  (atomic pointer)
      manifest_000007.json   {"segments": [{name, doc_base, n_docs, ...}]}
      segments/
        v0001/  v0002/ ...   self-contained impacted-list artifacts
                             (serving/artifact.py layout, counts=True)

Spark-Streaming/Lucene correspondence: a streaming commit point *seals* a
segment (Lucene: ``IndexWriter.commit`` flushing an immutable segment; the
analog of a micro-batch landing in a sink), the manifest flip is the
`segments_N` generation file, and the background :class:`SegmentMerger`
is the tiered merge policy compacting small segments so the live set — and
the per-query merge fan-out — stays bounded.

Each segment carries **segment-local DF** plus raw counts and doc lengths
(``save_index(..., counts=True)``), which is exactly what makes the set
self-describing: index-wide statistics are the *sum* of the live segments'
local statistics, so :func:`load_segment_set` re-weights every segment's
postings under global DF/N at load time — scoring across segments matches
a monolithic rebuild's semantics (global IDF drift included) without ever
re-ingesting committed documents.  Documents never span segments; each
segment owns the contiguous global doc-id range ``[doc_base, doc_base +
n_docs)``.

Concurrency: manifest commits are read-modify-write (append a sealed
segment / replace merged ones), serialized through the module commit lock
so an ingest seal and a background merge can never resurrect each other's
replaced segments.  Segment *artifacts* are immutable — readers holding an
older manifest keep valid (mmap'd) files; only segments replaced by a
committed merge are garbage-collected.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import tempfile
import threading
import time

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import TfidfOutput
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.serving.artifact import (
    ServableIndex,
    _term_sorted,
    build_term_offsets,
    load_index,
    save_index,
)
from page_rank_and_tfidf_using_apache_spark_tpu.utils import checkpoint as ckpt
from page_rank_and_tfidf_using_apache_spark_tpu.utils.config import (
    Bm25Config,
    IdfMode,
    TfidfConfig,
    TfMode,
)

SEGMENTS_SUBDIR = "segments"
_MANIFEST_RE = re.compile(r"^manifest_(\d{6})\.json$")

# Chaos/retry site of the background compaction (tools/chaos.sh segment
# scenario + tests/test_segments.py name it): a transient fault mid-merge
# retries; a persistent one skips the tick — the live set just stays
# unmerged until the next pass.
MERGE_SITE = "segment_merge"

# Serializes manifest read-modify-write commits (ingest append vs merge
# replace) within one process; artifact writes themselves are atomic.
_COMMIT_LOCK = threading.Lock()


@dataclasses.dataclass(frozen=True)
class SegmentRef:
    """One live segment as the manifest names it."""

    name: str  # version dir name under segments/ (e.g. "v0001")
    doc_base: int  # global doc-id base of this segment's range
    n_docs: int
    nnz: int

    def to_json(self) -> dict:
        # an explicit literal, not dataclasses.asdict: the tier-5
        # schema-pair-drift check validates written vs read manifest keys
        # lexically, so the writer side must be visible to the AST
        return {"name": self.name, "doc_base": self.doc_base,
                "n_docs": self.n_docs, "nnz": self.nnz}

    @classmethod
    def from_json(cls, d: dict) -> "SegmentRef":
        return cls(name=d["name"], doc_base=int(d["doc_base"]),
                   n_docs=int(d["n_docs"]), nnz=int(d["nnz"]))


@dataclasses.dataclass(frozen=True)
class Manifest:
    """One committed generation of the live segment set, base-ordered."""

    version: int
    config_hash: str
    segments: tuple[SegmentRef, ...]

    @property
    def n_docs(self) -> int:
        return sum(s.n_docs for s in self.segments)

    @property
    def nnz(self) -> int:
        return sum(s.nnz for s in self.segments)


def _manifest_name(version: int) -> str:
    return f"manifest_{version:06d}.json"


def manifest_version(directory: str) -> int | None:
    """Cheap poll of the committed manifest generation (None = the
    directory is not a segmented index yet): reads only the pointer."""
    ptr = os.path.join(directory, "LATEST")
    try:
        with open(ptr) as f:
            name = f.read().strip()
    except FileNotFoundError:
        return None
    m = _MANIFEST_RE.match(name)
    return int(m.group(1)) if m else None


def latest_manifest(directory: str) -> Manifest | None:
    ver = manifest_version(directory)
    if ver is None:
        return None
    with open(os.path.join(directory, _manifest_name(ver))) as f:
        d = json.load(f)
    return Manifest(
        version=int(d["version"]),
        config_hash=d["config_hash"],
        segments=tuple(SegmentRef.from_json(s) for s in d["segments"]),
    )


def _replaced_by(directory: str, version: int) -> tuple[str, ...]:
    """Segment dir names the given manifest generation replaced (its
    deferred-GC list); () when none or the file is gone."""
    try:
        with open(os.path.join(directory, _manifest_name(version))) as f:
            return tuple(json.load(f).get("replaced", ()))
    except (FileNotFoundError, json.JSONDecodeError):
        return ()


def _write_manifest(directory: str, manifest: Manifest,
                    replaced: tuple[str, ...] = ()) -> int:
    """Atomically write the manifest file, then flip LATEST — a reader
    either sees the previous generation whole or this one whole.
    ``replaced`` records the segment dirs this generation superseded;
    they are garbage-collected one generation LATER (commit_replace), so
    a reader between ``latest_manifest()`` and opening the files always
    finds them."""
    name = _manifest_name(manifest.version)
    payload = {
        "version": manifest.version,
        "config_hash": manifest.config_hash,
        "n_docs": manifest.n_docs,
        "nnz": manifest.nnz,
        "replaced": list(replaced),
        "segments": [s.to_json() for s in manifest.segments],
    }
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        # the LATEST flip below makes this manifest pointer-visible: fsync
        # file + parent dir before the flip can name it (tier 5)
        ckpt.durable_replace(tmp, os.path.join(directory, name))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    ckpt._write_pointer(directory, name)
    obs.emit("segment_commit", version=manifest.version,
             segments=len(manifest.segments), n_docs=manifest.n_docs,
             nnz=manifest.nnz)
    obs.counter("segment_commits")
    return manifest.version


def seal_segment(
    directory: str,
    output: TfidfOutput,
    cfg: TfidfConfig,
    *,
    doc_base: int,
    ranks: np.ndarray | None = None,
    bm25: Bm25Config | None = Bm25Config(),
    extra: dict | None = None,
) -> SegmentRef:
    """Seal one immutable delta segment (NOT yet live — commit it with
    :func:`commit_append`).  ``output`` holds ONLY the delta documents,
    locally 0-indexed; ``doc_base`` places them in the global id space."""
    with obs.span("segment.seal", doc_base=doc_base, n_docs=output.n_docs,
                  nnz=output.nnz):
        path = save_index(
            os.path.join(directory, SEGMENTS_SUBDIR), output, cfg,
            ranks=ranks, bm25=bm25, counts=True,
            extra={"doc_base": int(doc_base), **(extra or {})},
        )
    return SegmentRef(name=os.path.basename(path), doc_base=int(doc_base),
                      n_docs=int(output.n_docs), nnz=int(output.nnz))


def commit_append(directory: str, ref: SegmentRef,
                  config_hash: str) -> int:
    """Commit a sealed segment into the live set: manifest generation
    version+1 with ``ref`` appended, LATEST flipped.  Returns the new
    manifest version — the moment the segment is *servable*."""
    with _COMMIT_LOCK:
        cur = latest_manifest(directory)
        if cur is not None and cur.config_hash != config_hash:
            raise ValueError(
                f"segmented index {directory} was committed under config "
                f"{cur.config_hash}; refusing to append a {config_hash} "
                "segment across semantic changes"
            )
        segs = (cur.segments if cur else ()) + (ref,)
        version = (cur.version if cur else 0) + 1
        return _write_manifest(directory, Manifest(
            version=version, config_hash=config_hash,
            segments=tuple(sorted(segs, key=lambda s: s.doc_base)),
        ))


def commit_replace(directory: str, old_names: tuple[str, ...],
                   new_ref: SegmentRef) -> int:
    """Commit a merge: the named segments leave the live set, the merged
    segment (covering exactly their doc range) enters it.  Replaced
    segment directories are deleted one generation DEFERRED: this commit
    deletes what the PREVIOUS generation replaced, and records its own
    replacements for the next one — so a reader that resolved the
    just-superseded manifest still finds every file it names."""
    with _COMMIT_LOCK:
        cur = latest_manifest(directory)
        if cur is None:
            raise FileNotFoundError(f"no committed manifest under {directory}")
        names = set(old_names)
        missing = names - {s.name for s in cur.segments}
        if missing:
            raise ValueError(f"segments not live, cannot replace: {missing}")
        gc_now = _replaced_by(directory, cur.version)
        segs = tuple(s for s in cur.segments if s.name not in names)
        segs = tuple(sorted(segs + (new_ref,), key=lambda s: s.doc_base))
        version = _write_manifest(directory, Manifest(
            version=cur.version + 1, config_hash=cur.config_hash,
            segments=segs,
        ), replaced=tuple(old_names))
    for name in gc_now:
        shutil.rmtree(os.path.join(directory, SEGMENTS_SUBDIR, name),
                      ignore_errors=True)
    return version


def gc_orphans(directory: str, *, min_age_s: float = 60.0) -> list[str]:
    """Crash-recovery sweep (``tools/crash_harness.py`` runs it after
    every SIGKILL; operators run it after any unclean shutdown): delete
    on-disk state that no committed generation names — ``*.tmp`` files a
    killed writer left behind, half-staged ``.vNNNN.*`` tmp directories,
    sealed segment directories that never made it into a manifest, and
    manifest generations NEWER than the LATEST pointer (a crash between
    the manifest write and the pointer flip).  The committed generation's
    segments and its deferred-GC (``replaced``) list are kept, so the
    sweep is safe beside readers of the current generation.

    The commit lock serializes the sweep against manifest commits, but
    sealing happens OUTSIDE that lock — a segment being sealed right now
    is indistinguishable from crash debris by name alone.  ``min_age_s``
    is the guard: only candidates whose mtime is at least that old are
    deleted (default 60s — far past any seal-to-commit window), so the
    sweep is safe on a LIVE index beside in-flight seals and merges.
    Pass ``min_age_s=0`` only when no writer can be running (the crash
    harness's post-kill verify).  Returns the deleted paths."""
    deleted: list[str] = []

    def _old_enough(path: str) -> bool:
        if min_age_s <= 0:
            return True
        try:
            return time.time() - os.path.getmtime(path) >= min_age_s
        except OSError:
            return False  # vanished underneath us — nothing to delete

    with _COMMIT_LOCK:
        cur = latest_manifest(directory)
        cur_version = 0
        keep: set[str] = set()
        if cur is not None:
            cur_version = cur.version
            keep = {s.name for s in cur.segments}
            keep |= set(_replaced_by(directory, cur.version))
        try:
            names = os.listdir(directory)
        except FileNotFoundError:
            return []
        for n in sorted(names):
            p = os.path.join(directory, n)
            if not _old_enough(p):
                continue
            if n.endswith(".tmp") and os.path.isfile(p):
                os.unlink(p)
                deleted.append(p)
            elif (m := _MANIFEST_RE.match(n)) and int(m.group(1)) > cur_version:
                os.unlink(p)  # written but never flipped to: unreachable
                deleted.append(p)
        seg_root = os.path.join(directory, SEGMENTS_SUBDIR)
        try:
            seg_names = os.listdir(seg_root)
        except FileNotFoundError:
            seg_names = []
        for n in sorted(seg_names):
            p = os.path.join(seg_root, n)
            if not _old_enough(p):
                continue
            if n.endswith(".tmp") and os.path.isfile(p):
                os.unlink(p)
                deleted.append(p)
            elif n.startswith(".") and os.path.isdir(p):
                shutil.rmtree(p, ignore_errors=True)  # mkdtemp staging dir
                deleted.append(p)
            elif os.path.isdir(p) and n not in keep:
                shutil.rmtree(p, ignore_errors=True)  # sealed, never named
                deleted.append(p)
    if deleted:
        obs.emit("segment_gc_orphans", directory=directory,
                 deleted=len(deleted), version=cur_version)
        obs.counter("segment_orphan_gcs")
    return deleted


def _seg_version(name: str) -> int:
    return int(name.lstrip("v"))


def load_segment(directory: str, ref: SegmentRef, *,
                 mmap: bool = True) -> ServableIndex:
    return load_index(os.path.join(directory, SEGMENTS_SUBDIR),
                      version=_seg_version(ref.name), mmap=mmap)


# ------------------------------------------------- global-stat re-weighting


def _host_idf(df: np.ndarray, n_docs: int, mode: IdfMode,
              dtype) -> np.ndarray:
    """Host mirror of ops.tfidf.idf_vector over the SUMMED (global) DF."""
    n = dtype.type(max(n_docs, 1))
    safe = np.maximum(df, 1.0).astype(dtype)
    if mode is IdfMode.CLASSIC:
        idf = np.log(n / safe)
    elif mode is IdfMode.MLLIB:
        idf = np.log((n + 1.0) / (df.astype(dtype) + 1.0))
    elif mode is IdfMode.SMOOTH:
        idf = np.log((1.0 + n) / (1.0 + df.astype(dtype))) + 1.0
    else:
        raise ValueError(f"unknown idf mode {mode}")
    return np.where(df > 0, idf, 0.0).astype(dtype)


def _host_tfidf_weights(seg: ServableIndex, idf_global: np.ndarray,
                        cfg: TfidfConfig) -> np.ndarray:
    dtype = idf_global.dtype
    count = np.asarray(seg.count, dtype)
    doc = np.asarray(seg.doc)
    dl = np.asarray(seg.doc_lengths)
    if cfg.tf_mode is TfMode.RAW:
        tf = count
    elif cfg.tf_mode is TfMode.FREQ:
        tf = count / np.maximum(dl[doc].astype(dtype), 1.0)
    else:  # LOGNORM
        tf = np.where(count > 0, 1.0 + np.log(np.maximum(count, 1.0)),
                      0.0).astype(dtype)
    w = tf * idf_global[np.asarray(seg.term)]
    if cfg.l2_normalize:
        sq = np.zeros(seg.n_docs, dtype)
        np.add.at(sq, doc, w * w)
        w = w / np.sqrt(np.maximum(sq, 1e-30))[doc]
    return w.astype(dtype)


def _host_bm25_weights(seg: ServableIndex, df_global: np.ndarray,
                       n_total: int, avgdl: float,
                       bm25: Bm25Config) -> np.ndarray:
    """Host mirror of dataflow.bm25.bm25_weights under INDEX-WIDE stats
    (global df, global N, global average doc length)."""
    dtype = df_global.dtype
    count = np.asarray(seg.count, dtype)
    dl = np.asarray(seg.doc_lengths)[np.asarray(seg.doc)].astype(dtype)
    df_pair = df_global[np.asarray(seg.term)]
    n = dtype.type(max(n_total, 1))
    idf = np.log1p((n - df_pair + 0.5) / (df_pair + 0.5))
    tf = count * (bm25.k1 + 1.0) / (
        count + bm25.k1 * (1.0 - bm25.b + bm25.b * dl / dtype.type(avgdl))
    )
    return (idf * tf).astype(dtype)


@dataclasses.dataclass(frozen=True)
class LoadedSegment:
    """One live segment ready for a server to device_put: the artifact
    plus its global placement and the index-wide re-weighted tables."""

    index: ServableIndex
    ref: SegmentRef
    weights: dict  # ranker -> np.ndarray [nnz] under GLOBAL statistics
    # int64 [vocab + 1] host slice table; None ONLY for a legacy
    # (pre-offsets, non-term-sorted) artifact — such a set serves via
    # the COO path only (the server refuses scoring="impacted" on it)
    term_offsets: np.ndarray | None


def _safe_offsets(index: ServableIndex, vocab: int) -> np.ndarray | None:
    """CSC offsets for a loaded artifact — derived ONLY when the postings
    really are term-sorted.  A legacy chunk-major streaming artifact is
    not, and bincount-derived offsets over it would describe runs that do
    not exist: silently wrong impacted scores.  None = COO-only."""
    if index.term_offsets is not None:
        return np.asarray(index.term_offsets)
    term = np.asarray(index.term)
    if _term_sorted(np.asarray(index.doc), term):
        return build_term_offsets(term, vocab)
    return None


@dataclasses.dataclass(frozen=True)
class SegmentSet:
    """The loaded live set of one manifest generation — what a server
    serves across (and hot-swaps to on refresh)."""

    directory: str
    manifest: Manifest
    segments: tuple[LoadedSegment, ...]
    cfg: TfidfConfig
    df_global: np.ndarray

    @property
    def version(self) -> int:
        return self.manifest.version

    @property
    def n_docs(self) -> int:
        return self.manifest.n_docs

    @property
    def nnz(self) -> int:
        return self.manifest.nnz

    @property
    def vocab_bits(self) -> int:
        return self.cfg.vocab_bits

    @property
    def has_bm25(self) -> bool:
        return all("bm25" in s.weights for s in self.segments)

    @property
    def has_ranks(self) -> bool:
        return all(s.index.ranks is not None for s in self.segments)


def load_segment_set(directory: str, *, mmap: bool = True,
                     expect_config_hash: str | None = None) -> SegmentSet:
    """Load the committed live set and re-weight every segment's postings
    under index-wide statistics (global DF = Σ segment-local DF, global
    N = Σ segment docs, global avgdl) so cross-segment scoring matches a
    monolithic rebuild — the whole point of carrying segment-local DF."""
    manifest = raw = None
    for attempt in range(3):
        manifest = latest_manifest(directory)
        if manifest is None:
            raise FileNotFoundError(
                f"no committed segment manifest under {directory!r} "
                "(seal one with serving.segments.seal_segment + "
                "commit_append)"
            )
        if (expect_config_hash is not None
                and manifest.config_hash != expect_config_hash):
            raise ValueError(
                f"segmented index {directory} was committed under config "
                f"{manifest.config_hash}, but current config is "
                f"{expect_config_hash}; refusing to serve across semantic "
                "changes"
            )
        try:
            raw = [load_segment(directory, ref, mmap=mmap)
                   for ref in manifest.segments]
            break
        except FileNotFoundError:
            # a concurrent merge superseded this generation and its
            # deferred GC caught up with a segment we were about to open
            # — the NEWEST manifest's files cannot be GC'd before a
            # further commit, so re-resolving wins immediately
            if attempt == 2:
                raise
    with obs.span("segment.load_set", version=manifest.version,
                  segments=len(manifest.segments)):
        cfg = raw[0].cfg
        dtype = np.asarray(raw[0].weight[:0]).dtype
        df_global = np.zeros(cfg.vocab_size, dtype)
        n_total = manifest.n_docs
        total_len = 0
        rescore = all(
            s.count is not None and s.doc_lengths is not None for s in raw
        )
        for s in raw:
            df_global += np.asarray(s.df, dtype)
            if rescore:
                total_len += int(np.asarray(s.doc_lengths).sum())
        avgdl = max(total_len / max(n_total, 1), 1.0)
        idf_global = _host_idf(df_global, n_total, cfg.idf_mode,
                               np.dtype(dtype))
        loaded = []
        for s, ref in zip(raw, manifest.segments):
            if rescore:
                weights = {"tfidf": _host_tfidf_weights(s, idf_global, cfg)}
                bm25_cfg = s.extra.get("bm25_config")
                if bm25_cfg is not None:
                    weights["bm25"] = _host_bm25_weights(
                        s, df_global, n_total, avgdl, Bm25Config(**bm25_cfg)
                    )
            else:
                # a plain (counts-less) artifact wrapped as a one-segment
                # set: serve its stored tables verbatim
                weights = {"tfidf": np.ascontiguousarray(s.weight)}
                if s.bm25_weight is not None:
                    weights["bm25"] = np.ascontiguousarray(
                        s.bm25_weight.astype(dtype)
                    )
            loaded.append(LoadedSegment(
                index=s, ref=ref, weights=weights,
                term_offsets=_safe_offsets(s, cfg.vocab_size),
            ))
    return SegmentSet(directory=directory, manifest=manifest,
                      segments=tuple(loaded), cfg=cfg, df_global=df_global)


def wrap_index_as_set(index: ServableIndex) -> SegmentSet:
    """A plain monolithic :class:`ServableIndex` as a one-segment live
    set (doc_base 0) — the server's uniform internal representation."""
    ref = SegmentRef(name=os.path.basename(index.path), doc_base=0,
                     n_docs=index.n_docs, nnz=index.nnz)
    dtype = np.asarray(index.weight[:0]).dtype
    weights = {"tfidf": np.ascontiguousarray(index.weight)}
    if index.bm25_weight is not None:
        weights["bm25"] = np.ascontiguousarray(
            index.bm25_weight.astype(dtype))
    offsets = _safe_offsets(index, index.vocab_size)
    manifest = Manifest(version=index.version,
                        config_hash=index.cfg.config_hash(),
                        segments=(ref,))
    return SegmentSet(
        directory=os.path.dirname(index.path), manifest=manifest,
        segments=(LoadedSegment(index=index, ref=ref, weights=weights,
                                term_offsets=offsets),),
        cfg=index.cfg, df_global=np.asarray(index.df, dtype),
    )


# ------------------------------------------------------------------ merging


def merge_segments(directory: str, refs: tuple[SegmentRef, ...],
                   cfg: TfidfConfig) -> SegmentRef:
    """Compact adjacent segments into one sealed segment covering their
    combined contiguous doc range (NOT yet live — commit with
    :func:`commit_replace`).  Postings are re-sorted (term, doc) over the
    merged id space; local DF adds exactly (each (term, doc) pair lives in
    exactly one segment)."""
    refs = tuple(sorted(refs, key=lambda r: r.doc_base))
    for a, b in zip(refs, refs[1:]):
        if a.doc_base + a.n_docs != b.doc_base:
            raise ValueError(
                f"segments are not doc-contiguous: {a.name} ends at "
                f"{a.doc_base + a.n_docs}, {b.name} starts at {b.doc_base}"
            )
    base = refs[0].doc_base
    segs = [load_segment(directory, r, mmap=False) for r in refs]
    for s in segs:
        if s.count is None or s.doc_lengths is None:
            raise ValueError(
                f"segment {s.path} carries no raw counts — only "
                "counts=True segments are mergeable"
            )
    dtype = np.asarray(segs[0].weight[:0]).dtype
    doc = np.concatenate([
        np.asarray(s.doc, np.int64) + (r.doc_base - base)
        for s, r in zip(segs, refs)
    ]).astype(np.int32)
    term = np.concatenate([np.asarray(s.term) for s in segs])
    count = np.concatenate([np.asarray(s.count, dtype) for s in segs])
    perm = np.lexsort((doc, term))
    doc, term, count = doc[perm], term[perm], count[perm]
    doc_lengths = np.concatenate(
        [np.asarray(s.doc_lengths, np.int32) for s in segs])
    df = np.zeros(cfg.vocab_size, dtype)
    for s in segs:
        df += np.asarray(s.df, dtype)
    n_docs = sum(r.n_docs for r in refs)
    idf = _host_idf(df, n_docs, cfg.idf_mode, np.dtype(dtype))
    ranks = None
    if all(s.ranks is not None for s in segs):
        ranks = np.concatenate([np.asarray(s.ranks) for s in segs])
    # the merged weight table under SEGMENT-LOCAL stats, like any sealed
    # segment's (serve-time re-weighting under global stats supersedes it)
    w = _host_tfidf_weights(
        dataclasses.replace(
            segs[0], doc=doc, term=term, count=count,
            doc_lengths=doc_lengths, n_docs=n_docs,
        ),
        idf, cfg,
    )
    from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import (
        MetricsRecorder,
    )

    out = TfidfOutput(
        n_docs=n_docs, vocab_bits=cfg.vocab_bits, doc=doc, term=term,
        weight=w, df=df, idf=idf, metrics=MetricsRecorder(),
        count=count, doc_lengths=doc_lengths,
    )
    bm25_cfg = segs[0].extra.get("bm25_config")
    return seal_segment(
        directory, out, cfg, doc_base=base, ranks=ranks,
        bm25=Bm25Config(**bm25_cfg) if bm25_cfg is not None else None,
        extra={"merged_from": [r.name for r in refs]},
    )


def plan_merge(manifest: Manifest,
               max_segments: int) -> tuple[SegmentRef, ...] | None:
    """Tiered-merge policy: while the live set exceeds ``max_segments``,
    compact the ADJACENT pair with the smallest combined nnz (small
    deltas coalesce first; the big old segment is left alone until its
    neighbors grow comparable — Lucene's size-tiered intuition)."""
    segs = sorted(manifest.segments, key=lambda s: s.doc_base)
    if len(segs) <= max_segments:
        return None
    best = min(range(len(segs) - 1),
               key=lambda i: segs[i].nnz + segs[i + 1].nnz)
    return (segs[best], segs[best + 1])


class SegmentMerger:
    """Background compaction thread (declared in ``THREAD_REGISTRY`` as
    ``segment-merge``): every ``interval_s`` it loads the committed
    manifest and, while the live set exceeds ``max_segments``, merges the
    smallest adjacent pair and commits the replacement — under the
    resilience executor at the ``segment_merge`` site, so transient chaos
    retries and a persistent fault skips the tick (the set just stays
    unmerged; nothing serving-side depends on a merge happening)."""

    def __init__(self, directory: str, cfg: TfidfConfig, *,
                 max_segments: int = 4, interval_s: float = 1.0):
        self.directory = directory
        self.cfg = cfg
        self.max_segments = max(int(max_segments), 1)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._merges = 0
        self._errors = 0
        self._thread: threading.Thread | None = None

    def start(self) -> "SegmentMerger":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="segment-merge", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def __enter__(self) -> "SegmentMerger":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def merges(self) -> int:
        with self._lock:
            return self._merges

    def merge_once(self) -> bool:
        """One compaction step (also the testable unit): merge + commit
        the planned pair, True when a merge landed."""
        manifest = latest_manifest(self.directory)
        if manifest is None:
            return False
        pair = plan_merge(manifest, self.max_segments)
        if pair is None:
            return False
        with obs.span("segment.merge", a=pair[0].name, b=pair[1].name,
                      nnz=pair[0].nnz + pair[1].nnz):
            ref = rx.run_guarded(
                lambda: merge_segments(self.directory, pair, self.cfg),
                site=MERGE_SITE,
            )
            commit_replace(self.directory, (pair[0].name, pair[1].name), ref)
        with self._lock:
            self._merges += 1
        obs.emit("segment_merged", into=ref.name,
                 merged=[pair[0].name, pair[1].name], nnz=ref.nnz)
        obs.counter("segment_merges")
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                # drain the backlog: repeated merges until within policy
                while self.merge_once():
                    if self._stop.is_set():
                        break
            except Exception as exc:  # noqa: BLE001 — a failed merge must
                # never take serving down; the next tick retries from the
                # committed manifest (merge is idempotent-by-replacement)
                with self._lock:
                    self._errors += 1
                obs.emit("segment_merge_failed",
                         error=f"{type(exc).__name__}: {exc}"[:200])
                obs.counter("segment_merge_failures")
                time.sleep(min(self.interval_s, 0.2))
