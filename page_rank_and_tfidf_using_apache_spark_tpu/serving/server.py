"""Long-lived TF-IDF query server: warm compiled runners, padded
micro-batches, device-fused top-k, hot-query LRU cache (ISSUE 8).

Request lifecycle::

    submit(terms) ──► bounded queue ──► drain thread ──► LRU cache?
                                                 │ miss
                                                 ▼
                      pad to batch cap (grow_chunk_cap, min_bits=0)
                                                 ▼
                      ops.score_query_batch  (ONE jit dispatch, top-k
                      fused on device — full score vectors never cross
                      device→host)
                                                 ▼
                      guarded pull ──► per-request futures resolve

Design points, each load-bearing for the acceptance gates:

- **Finite batch-shape matrix.**  A micro-batch of ``b`` misses pads to
  ``grow_chunk_cap(b, 0, min_bits=0)`` — the next power of two — clipped
  by ``max_batch``, so the only shapes that ever reach jit are
  ``{1, 2, 4, ..., max_batch}``.  :func:`TfidfServer.warmup` compiles all
  of them up front; the ``tfidf_score_query_batch`` registry entry traces
  the same matrix, so tier-2 *proves* zero per-request recompiles.
- **Resilience.**  The dispatch and the pull run under the resilience
  executor (sites ``serve_dispatch`` / ``serve_pull``): transient faults
  retry invisibly; a persistent fault fails exactly the requests of the
  batch that hit it — the queue keeps draining (chaos-tested at
  ``serve_dispatch:fail@%5`` and a hard ``lost``).
- **Telemetry.**  Every batch is a ``serve.batch`` span with ``serve.pad``
  / ``serve.dispatch`` / ``serve.pull`` children; every request publishes
  a ``serve_request`` event carrying queue-wait and total latency, so
  ``tools/trace_report.py`` renders queue-wait vs pad vs dispatch vs pull
  and per-request p50/p99 from the artifact alone.
- **LRU.**  Results are cached under a hash of the *canonical* query
  vector (term-id-sorted, duplicate terms combined), so "foo bar" and
  "bar foo" hit the same entry; hits resolve on the drain thread without
  touching the device and publish ``serve.cache_hits`` counters.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import queue
import threading
import time
from typing import Sequence

import numpy as np

from page_rank_and_tfidf_using_apache_spark_tpu import obs
from page_rank_and_tfidf_using_apache_spark_tpu.io import text as tio
from page_rank_and_tfidf_using_apache_spark_tpu.models.tfidf import grow_chunk_cap
from page_rank_and_tfidf_using_apache_spark_tpu.ops import tfidf as ops
from page_rank_and_tfidf_using_apache_spark_tpu.resilience import executor as rx
from page_rank_and_tfidf_using_apache_spark_tpu.serving.artifact import ServableIndex
from page_rank_and_tfidf_using_apache_spark_tpu.utils.metrics import MetricsRecorder


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Operational knobs of one server instance (semantics live in the
    index artifact's TfidfConfig — a server never re-interprets weights)."""

    top_k: int = 10
    max_batch: int = 8  # micro-batch cap; padded shapes are pow2 <= this
    max_query_terms: int = 16  # Q: fixed per-query sparse slot count
    queue_depth: int = 64  # bound on submitted-but-undrained requests
    flush_ms: float = 2.0  # how long the drain waits to fill a batch
    cache_size: int = 1024  # LRU entries (0 disables the result cache)
    rank_alpha: float = 0.0  # additive PageRank-prior scale (0 = off),
    # applied to EVERY request (the server-level blend)
    prior_alpha: float = 0.0  # per-REQUEST PageRank-prior scale: > 0
    # enables ranker="prior" (tfidf weights + prior_alpha * ranks for
    # exactly the requests that opt in); the prior rides as a traced
    # operand, so the compiled batch matrix is shared with tfidf/bm25

    def __post_init__(self) -> None:
        if self.top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_query_terms < 1:
            raise ValueError(
                f"max_query_terms must be >= 1, got {self.max_query_terms}"
            )
        if self.queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self.queue_depth}")
        if self.cache_size < 0 or self.rank_alpha < 0 or self.prior_alpha < 0:
            raise ValueError(
                "cache_size, rank_alpha and prior_alpha must be >= 0"
            )


def batch_cap(b: int, max_batch: int, metrics: MetricsRecorder) -> int:
    """The serving micro-batcher's padding policy: literally
    :func:`models.tfidf.grow_chunk_cap` with ``min_bits=0`` and no carried
    cap — a batch of ``b`` pads to the next power of two, clipped by
    ``max_batch``.  One policy, two call sites, one lint surface."""
    cap, _ = grow_chunk_cap(min(b, max_batch), 0, metrics, min_bits=0)
    return min(cap, max_batch)


def batch_shape_matrix(max_batch: int) -> list[int]:
    """Every padded batch size the policy can produce: the finite shape
    matrix warmup compiles and the tier-2 recompile gate traces."""
    caps: list[int] = []
    metrics = MetricsRecorder()
    for b in range(1, max_batch + 1):
        c = batch_cap(b, max_batch, metrics)
        if c not in caps:
            caps.append(c)
    return caps


def serve_pad_plan(
    batch_sizes: Sequence[int], max_batch: int = 8
) -> list[tuple[str, float]]:
    """Static padding-waste plan of the serving micro-batcher: run raw
    batch sizes through the REAL :func:`batch_cap` policy and return
    ``[("serve", pad_frac)]`` — the tier-3 pad_frac surface for the
    batched query entry point, the serving counterpart of
    ``models.tfidf.stream_pad_plan``."""
    metrics = MetricsRecorder()
    total_raw = 0
    total_cap = 0
    for b in batch_sizes:
        total_raw += min(int(b), max_batch)
        total_cap += batch_cap(int(b), max_batch, metrics)
    pad_frac = (total_cap - total_raw) / max(total_cap, 1)
    return [("serve", pad_frac)]


# "prior" scores with the tfidf weight table plus the per-request
# PageRank-prior blend (ServeConfig.prior_alpha) — the third traffic class
# of the soak's mixed workload.  All rankers share every compiled
# executable: the weight table AND the prior vector are traced operands.
RANKERS = ("tfidf", "bm25", "prior")


class _Pending:
    """One in-flight request: a tiny future the drain thread resolves."""

    __slots__ = ("key", "q_term", "q_weight", "ranker", "t_submit", "t_done",
                 "t_queue_wait", "cache", "_event", "_result", "_error")

    def __init__(self, key: bytes, q_term: np.ndarray, q_weight: np.ndarray,
                 ranker: str = "tfidf"):
        self.key = key
        self.q_term = q_term
        self.q_weight = q_weight
        self.ranker = ranker
        self.t_submit = time.perf_counter()
        self.t_done: float | None = None
        self.t_queue_wait = 0.0
        self.cache = "miss"
        self._event = threading.Event()
        self._result: tuple[np.ndarray, np.ndarray] | None = None
        self._error: BaseException | None = None

    def _resolve(self, result: tuple[np.ndarray, np.ndarray]) -> None:
        self._result = result
        self.t_done = time.perf_counter()
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self.t_done = time.perf_counter()
        self._event.set()

    @property
    def done(self) -> bool:
        """True once the request resolved or failed (non-blocking)."""
        return self._event.is_set()

    @property
    def error(self) -> BaseException | None:
        """The failure that resolved this request, or None (non-blocking;
        the soak's double-serve audit inspects abandoned futures)."""
        return self._error

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def result(self, timeout: float | None = None) -> tuple[np.ndarray, np.ndarray]:
        """Block for this request's ``(scores[k], doc_ids[k])``; re-raises
        the batch's failure when its dispatch exhausted the ladder."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request did not complete in time")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


_STOP = object()


class TfidfServer:
    """The long-lived online query path over one :class:`ServableIndex`.

    Usage::

        index = serving.load_index("/path/to/index")
        with TfidfServer(index, ServeConfig(top_k=10)) as srv:
            scores, docs = srv.query(["apollo", "guidance"])

    ``start()`` device-puts the postings once and (by default) warms every
    padded batch shape, so steady state never compiles; ``submit`` is
    thread-safe and returns a future.
    """

    def __init__(
        self,
        index: ServableIndex,
        cfg: ServeConfig = ServeConfig(),
        *,
        metrics: MetricsRecorder | None = None,
    ):
        if index.n_docs < 1 or index.nnz < 1:
            raise ValueError("cannot serve an empty index")
        if (cfg.rank_alpha > 0 or cfg.prior_alpha > 0) and index.ranks is None:
            raise ValueError(
                "rank_alpha/prior_alpha > 0 needs a PageRank prior in the "
                "index (save_index(..., ranks=...))"
            )
        self.index = index
        self.cfg = cfg
        self.metrics = metrics or MetricsRecorder()
        self.k = min(cfg.top_k, index.n_docs)
        self._queue: queue.Queue = queue.Queue(maxsize=cfg.queue_depth)
        self._thread: threading.Thread | None = None
        self._started = False
        self._valid = None
        self._weights: dict = {}
        self._cache: collections.OrderedDict[bytes, tuple] = collections.OrderedDict()
        self._lock = threading.Lock()  # cache + stats
        # Orders submit()'s {started-check, enqueue} against stop()'s flag
        # flip.  Deliberately NOT self._lock: the drain thread takes that
        # one per batch, and a submitter may block on a full queue while
        # holding this lock — the drain must be free to keep consuming.
        self._submit_lock = threading.Lock()
        self._stats = collections.Counter()
        self._dev: tuple | None = None  # device-resident postings
        self._prior = None  # every-request prior operand (rank_alpha blend)
        self._prior_req = None  # ranker="prior" operand (+= prior_alpha)
        self._prior_gen = 0  # bumped per operand swap; stale-cache guard
        self._use_prior = False
        self._runner = None

    # ------------------------------------------------------------ lifecycle

    def start(self, warm: bool = True) -> "TfidfServer":
        """Load device state and launch the drain thread.  ``warm=True``
        compiles every padded batch shape before the first request."""
        if self._started:
            return self
        import jax.numpy as jnp

        idx = self.index
        with obs.span("serve.load", version=idx.version, nnz=idx.nnz):
            # the artifact arrays are mmap views; device_put pages them in
            # exactly once, then queries touch only device memory.  The
            # per-ranker weight tables live side by side over the SAME
            # doc/term postings; ranker selection swaps a traced operand,
            # never a program.
            self._dev = (
                jnp.asarray(np.ascontiguousarray(idx.doc)),
                jnp.asarray(np.ascontiguousarray(idx.term)),
            )
            self._valid = jnp.ones(idx.nnz, idx.weight.dtype)
            self._weights = {
                "tfidf": jnp.asarray(np.ascontiguousarray(idx.weight)),
            }
            if idx.bm25_weight is not None:
                self._weights["bm25"] = jnp.asarray(
                    np.ascontiguousarray(
                        idx.bm25_weight.astype(idx.weight.dtype)
                    )
                )
            self._use_prior = (
                self.cfg.rank_alpha > 0 or self.cfg.prior_alpha > 0
            )
            self._set_prior_arrays(
                np.ascontiguousarray(idx.ranks)
                if idx.ranks is not None else None
            )
        self._runner = functools.partial(
            ops.score_query_batch,
            n_docs=idx.n_docs,
            vocab=idx.vocab_size,
            k=self.k,
            use_prior=self._use_prior,
        )
        self._started = True
        if warm:
            self.warmup()
        self._thread = threading.Thread(
            target=self._drain, name="tfidf-serve-drain", daemon=True
        )
        self._thread.start()
        obs.emit("serve_start", version=idx.version, n_docs=idx.n_docs,
                 nnz=idx.nnz, k=self.k, max_batch=self.cfg.max_batch)
        return self

    def warmup(self) -> list[int]:
        """Compile (and fence) every padded batch shape the policy can
        produce.  After this, a request can only ever hit a warm
        executable — the 'compiled runners warm' half of the tentpole.
        One pass covers BOTH rankers: the weight table is a traced
        operand of the same shape/dtype, so tfidf and bm25 share every
        compiled executable."""
        caps = batch_shape_matrix(self.cfg.max_batch)
        q = self.cfg.max_query_terms
        for cap in caps:
            with obs.span("serve.warmup", batch=cap):
                zt = np.zeros((cap, q), np.int32)
                zw = np.zeros((cap, q), self.index.weight.dtype)
                out = self._runner(
                    *self._dev, self._weights["tfidf"], self._valid,
                    zt, zw, zw, self._prior,
                )
                rx.block_until_ready(
                    out, site="serve_warmup", metrics=self.metrics
                )
        return caps

    def _set_prior_arrays(self, ranks: np.ndarray | None) -> None:
        """(Re)build the two device-resident prior operands from a host
        ranks vector: the every-request blend (``rank_alpha * ranks``) and
        the ranker="prior" blend (``(rank_alpha + prior_alpha) * ranks``).
        Zeros when the server carries no prior."""
        import jax.numpy as jnp

        dtype = self.index.weight.dtype
        n = self.index.n_docs
        if ranks is None or not self._use_prior:
            base = np.zeros(n, dtype)
            req = base
        else:
            ranks = np.ascontiguousarray(ranks, dtype)
            base = (self.cfg.rank_alpha * ranks if self.cfg.rank_alpha > 0
                    else np.zeros(n, dtype))
            req = base + self.cfg.prior_alpha * ranks
        base_dev = jnp.asarray(base.astype(dtype))
        req_dev = (base_dev if req is base
                   else jnp.asarray(req.astype(dtype)))
        with self._lock:
            self._prior = base_dev
            self._prior_req = req_dev
            self._prior_gen += 1

    def set_prior(self, ranks: np.ndarray) -> None:
        """Hot-swap the PageRank prior on a RUNNING server (the soak's
        background refresh): rebuilds the prior operands from ``ranks``
        and invalidates the result cache (cached top-k blended the old
        prior).  No recompile — the prior is a traced operand of every
        warm executable.  Requires a server constructed with
        ``rank_alpha > 0`` or ``prior_alpha > 0`` (otherwise the compiled
        program has no prior addend to feed)."""
        if not self._started:
            raise RuntimeError("server not started")
        if not self._use_prior:
            raise RuntimeError(
                "server compiled without a prior operand — construct with "
                "ServeConfig(rank_alpha=... ) or ServeConfig(prior_alpha=...)"
            )
        ranks = np.ascontiguousarray(ranks)
        if ranks.shape != (self.index.n_docs,):
            raise ValueError(
                f"prior has shape {ranks.shape}; this index holds "
                f"{self.index.n_docs} documents"
            )
        self._set_prior_arrays(ranks)
        with self._lock:
            self._cache.clear()
        obs.emit("serve_prior_update", n_docs=int(ranks.shape[0]))

    def stop(self) -> None:
        with self._submit_lock:
            self._started = False  # new submits refuse from here on
        if self._thread is not None:
            self._queue.put(_STOP)
            self._thread.join()
            self._thread = None
        # A submit racing this shutdown can still have slipped a request in
        # around the sentinel; with the drain thread gone, fail it rather
        # than leave its future hanging forever.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, _Pending):
                item._fail(RuntimeError("server stopped"))
        obs.emit("serve_stop", **{k: int(v) for k, v in self._stats.items()})

    def __enter__(self) -> "TfidfServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- queries

    def make_query(self, terms: Sequence[str]) -> tuple[np.ndarray, np.ndarray]:
        """Host-side query prep: run the query through the INDEX's real
        tokenizer pipeline (``io.text.tokenize`` + ``add_ngrams`` with the
        artifact's config — so "state-of-the-art" splits exactly like the
        corpus did, and an ngram=2 index gets its bigram terms), then hash
        into canonical (term_ids, weights) — term-id-sorted, duplicates
        combined (weight = occurrence count, the A11 query vector),
        truncated to the ``max_query_terms`` hot slots."""
        cfg = self.index.cfg
        toks: list[str] = []
        for t in terms:
            toks.extend(tio.tokenize(t, lowercase=cfg.lowercase,
                                     min_token_len=cfg.min_token_len))
        toks = tio.add_ngrams(toks, cfg.ngram)
        if not toks:
            return (np.zeros(0, np.int32),
                    np.zeros(0, self.index.weight.dtype))
        ids = tio.hash_to_vocab(tio.fnv1a_64(toks), self.index.vocab_bits)
        uniq, counts = np.unique(ids, return_counts=True)
        if uniq.shape[0] > self.cfg.max_query_terms:
            # keep the heaviest terms; stable enough for a hot path and
            # recorded so operators see truncation happening
            order = np.argsort(-counts, kind="stable")[: self.cfg.max_query_terms]
            order.sort()
            uniq, counts = uniq[order], counts[order]
            obs.counter("serve.query_truncated")
        return uniq.astype(np.int32), counts.astype(self.index.weight.dtype)

    @staticmethod
    def query_key(q_term: np.ndarray, q_weight: np.ndarray,
                  ranker: str = "tfidf") -> bytes:
        """LRU key: hash of the canonical sparse query vector + the
        ranker that scored it (an A/B pair must never share a cache
        entry)."""
        h = hashlib.sha1()
        h.update(ranker.encode())
        h.update(q_term.tobytes())
        h.update(q_weight.tobytes())
        return h.digest()

    def submit(self, terms: Sequence[str], *, ranker: str = "tfidf") -> _Pending:
        """Enqueue one query; returns a future.  Blocks when the bounded
        queue is full (backpressure, not unbounded memory).  ``ranker``
        picks the weight table per request (the A/B switch): ``tfidf``
        always, ``bm25`` when the index artifact bundles BM25 weights."""
        if ranker not in RANKERS:
            raise ValueError(f"unknown ranker {ranker!r} (want {RANKERS})")
        if ranker == "bm25" and self.index.bm25_weight is None:
            raise ValueError(
                "this index carries no BM25 weights — rebuild with "
                "save_index(..., bm25=Bm25Config()) / cli.tfidf "
                "--save-index (BM25 is bundled by default)"
            )
        if ranker == "prior" and self.cfg.prior_alpha <= 0:
            raise ValueError(
                "ranker='prior' needs a per-request prior scale — construct "
                "the server with ServeConfig(prior_alpha=...) over an index "
                "saved with a ranks prior"
            )
        q_term, q_weight = self.make_query(terms)
        pending = _Pending(self.query_key(q_term, q_weight, ranker),
                           q_term, q_weight, ranker)
        with self._submit_lock:
            # the started-check AND the enqueue happen under the lock
            # stop() flips the flag under, so a racing submit either
            # raises here or its request is in the queue BEFORE the stop
            # sentinel (served, or failed by the leftover drain) — never
            # silently dropped with a hanging future
            if not self._started:
                raise RuntimeError("server not started")
            self._queue.put(pending)  # graftlint: disable=blocking-under-lock (deliberate: backpressure belongs inside the started-check; the drain consumes without ever taking _submit_lock, so a blocked put always unblocks — see the _submit_lock comment above)
        with self._lock:
            self._stats["requests"] += 1
            # per-ranker traffic split for the A/B read-out — counted at
            # submit so cache hits are included, unlike the per-dispatch
            # tallies in _serve_group
            self._stats[f"requests_{ranker}"] += 1
        return pending

    def query(
        self, terms: Sequence[str], timeout: float | None = 30.0,
        *, ranker: str = "tfidf",
    ) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous convenience wrapper: submit + wait."""
        return self.submit(terms, ranker=ranker).result(timeout)

    def stats(self) -> dict:
        with self._lock:
            out = {k: int(v) for k, v in self._stats.items()}
        out.setdefault("requests", 0)
        for key in ("cache_hits", "cache_misses", "dedup_hits", "batches",
                    "batch_errors"):
            out.setdefault(key, 0)
        return out

    # ---------------------------------------------------------- drain thread

    def _cache_get(self, key: bytes):
        if self.cfg.cache_size <= 0:
            return None
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
        return hit

    def _cache_put(self, key: bytes, value: tuple, gen: int) -> None:
        if self.cfg.cache_size <= 0:
            return
        with self._lock:
            if gen != self._prior_gen:
                # the batch was dispatched against a prior operand that
                # set_prior has since hot-swapped: caching it would serve
                # the stale blend as hits after the invalidation
                return
            self._cache[key] = value
            self._cache.move_to_end(key)
            while len(self._cache) > self.cfg.cache_size:
                self._cache.popitem(last=False)

    def _drain(self) -> None:
        """The micro-batching loop: block for one request, gather up to
        ``max_batch`` within ``flush_ms``, serve the batch, repeat."""
        while True:
            try:
                first = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if first is _STOP:
                return
            batch = [first]
            deadline = time.perf_counter() + self.cfg.flush_ms / 1e3
            stop_after = False
            while len(batch) < self.cfg.max_batch:
                wait = deadline - time.perf_counter()
                try:
                    item = (self._queue.get(timeout=wait) if wait > 0
                            else self._queue.get_nowait())
                except queue.Empty:
                    break
                if item is _STOP:
                    stop_after = True
                    break
                batch.append(item)
            try:
                self._serve_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the drain must survive
                # _serve_batch guards the dispatch/pull internally; this
                # catches everything else (pad bookkeeping, a misbehaving
                # caller-supplied metrics recorder, cache publication) so
                # the ONLY queue consumer never dies: the batch's futures
                # fail, later requests keep serving.
                with self._lock:
                    self._stats["batch_errors"] += 1
                obs.counter("serve.batch_errors")
                for p in batch:
                    if not p._event.is_set():
                        p._fail(exc)
            if stop_after:
                return

    def _publish_request(self, p: _Pending, batch: int, error: str | None = None) -> None:
        obs.emit(
            "serve_request",
            cache=p.cache,
            queue_wait_s=round(p.t_queue_wait, 6),
            total_s=round(p.latency_s or 0.0, 6),
            batch=batch,
            **({"error": error} if error else {}),
        )
        obs.histogram("serve.latency_s", p.latency_s or 0.0)
        obs.histogram("serve.queue_wait_s", p.t_queue_wait)

    def _serve_batch(self, batch: list[_Pending]) -> None:
        t_dequeue = time.perf_counter()
        for p in batch:
            p.t_queue_wait = t_dequeue - p.t_submit
        with obs.span("serve.batch", size=len(batch)):
            misses: list[_Pending] = []
            for p in batch:
                hit = self._cache_get(p.key)
                if hit is not None:
                    p.cache = "hit"
                    p._resolve(hit)
                    with self._lock:
                        self._stats["cache_hits"] += 1
                    obs.counter("serve.cache_hits")
                    self._publish_request(p, batch=len(batch))
                else:
                    misses.append(p)
            if not misses:
                return
            # Per-ranker groups: an A/B batch dispatches once per ranker
            # present (the weight table is a per-dispatch operand; shapes
            # — and therefore executables — are shared, so a mixed batch
            # still never compiles).  The overwhelmingly common case is
            # one ranker per flush window = one dispatch, exactly the
            # pre-A/B behavior.
            by_ranker: dict[str, list[_Pending]] = {}
            for p in misses:
                by_ranker.setdefault(p.ranker, []).append(p)
            for ranker, plist in by_ranker.items():
                self._serve_group(ranker, plist, batch_size=len(batch))

    def _serve_group(self, ranker: str, misses: list[_Pending],
                     *, batch_size: int) -> None:
        """Dedup, pad, dispatch and resolve one ranker's share of a
        micro-batch."""
        # In-batch dedup: N copies of one hot query arriving inside a
        # single flush window dispatch ONCE (the cache can only serve
        # repeats across batches; this closes the within-batch gap).
        groups: dict[bytes, list[_Pending]] = {}
        for p in misses:
            groups.setdefault(p.key, []).append(p)
        uniq = [ps[0] for ps in groups.values()]
        for ps in groups.values():
            for p in ps[1:]:
                p.cache = "dedup"
        with self._lock:
            self._stats["cache_misses"] += len(uniq)
            self._stats["dedup_hits"] += len(misses) - len(uniq)
            self._stats["batches"] += 1
        obs.counter("serve.cache_misses", len(uniq))

        q = self.cfg.max_query_terms
        cap = batch_cap(len(uniq), self.cfg.max_batch, self.metrics)
        with obs.span("serve.pad", size=len(uniq), cap=cap, ranker=ranker):
            dtype = self.index.weight.dtype
            q_term = np.zeros((cap, q), np.int32)
            q_weight = np.zeros((cap, q), dtype)
            q_valid = np.zeros((cap, q), dtype)
            for i, p in enumerate(uniq):
                m = min(p.q_term.shape[0], q)
                q_term[i, :m] = p.q_term[:m]
                q_weight[i, :m] = p.q_weight[:m]
                q_valid[i, :m] = 1.0
        # ranker="prior" is the tfidf table with the per-request prior
        # operand; tfidf/bm25 ride the every-request (rank_alpha) operand.
        # The (operand, generation) pair is read atomically so a set_prior
        # landing mid-batch cannot smuggle this batch's result past its
        # cache invalidation.
        table = self._weights["tfidf" if ranker == "prior" else ranker]
        with self._lock:
            prior = self._prior_req if ranker == "prior" else self._prior
            prior_gen = self._prior_gen
        try:
            with obs.span("serve.dispatch", cap=cap, ranker=ranker):
                scores_dev, idx_dev = rx.run_guarded(
                    lambda: self._runner(
                        *self._dev, table, self._valid,
                        q_term, q_weight, q_valid, prior,
                    ),
                    site="serve_dispatch", metrics=self.metrics,
                )
            with obs.span("serve.pull", cap=cap):
                # ONE batched [cap, k] pull — the only bytes that ever
                # cross device->host per batch
                scores, idx = rx.device_get(
                    (scores_dev, idx_dev), site="serve_pull",
                    metrics=self.metrics,
                )
        except Exception as exc:  # noqa: BLE001 — isolated per batch
            # fail exactly this group's requests; the drain loop (and
            # every other queued request) keeps going — per-request
            # degradation, not a server crash
            with self._lock:
                self._stats["batch_errors"] += 1
            obs.counter("serve.batch_errors")
            err = f"{type(exc).__name__}: {exc}"[:200]
            for p in misses:
                p._fail(exc)
                self._publish_request(p, batch=batch_size, error=err)
            return
        for i, key in enumerate(groups):
            result = (scores[i].copy(), idx[i].copy())
            self._cache_put(key, result, prior_gen)
            for p in groups[key]:
                p._resolve(result)
                self._publish_request(p, batch=batch_size)
